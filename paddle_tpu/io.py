"""Model persistence: save/load variables, parameters, persistables, and
inference models.

≙ reference python/paddle/fluid/io.py (save/load_vars:89, save/load_params,
save/load_persistables:252,464, save_inference_model:561,
load_inference_model:677) + save_op.cc:66 / load_op.cc:24 /
save_combine_op / load_combine_op.

TPU-first format choices: variables are host numpy arrays saved as one .npy
per var (≙ save_op one-file-per-var) or a single .npz (≙ save_combine);
programs serialize to JSON (paddle_tpu programs are small — the heavy
artifact is XLA's compiled executable, cached by the runtime). A
`save_as_bf16` flag mirrors the reference's `save_as_fp16` attr
(save_op.cc supports fp16 conversion on save).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from .core.enforce import InvalidArgumentError, NotFoundError, enforce
from .framework.executor import Executor, as_numpy
from .framework.program import (Parameter, Program, Variable,
                                default_main_program)
from .framework.scope import Scope, global_scope

INFERENCE_PROGRAM_FILE = "__model__"
PARAMS_COMBINED_FILE = "__params__.npz"


def _is_parameter(var: Variable) -> bool:
    return isinstance(var, Parameter)


def _is_persistable(var: Variable) -> bool:
    return bool(var.persistable)


def _select_vars(program: Program, predicate) -> List[Variable]:
    out = []
    seen = set()
    for b in program.blocks:
        for v in b.vars.values():
            if v.name not in seen and predicate(v):
                seen.add(v.name)
                out.append(v)
    return sorted(out, key=lambda v: v.name)


BF16_TAG = "@BF16"


def _maybe_bf16(arr: np.ndarray, save_as_bf16: bool) -> np.ndarray:
    if save_as_bf16 and arr.dtype == np.float32:
        import jax.numpy as jnp
        return np.asarray(jnp.asarray(arr).astype(jnp.bfloat16))
    return arr


def _encode_for_npy(name: str, arr: np.ndarray):
    """numpy cannot round-trip bfloat16 through .npy/.npz (comes back as
    raw void) — store the bit pattern as uint16 under a tagged name."""
    if arr.dtype.name == "bfloat16":
        return name + BF16_TAG, arr.view(np.uint16)
    return name, arr


def _decode_from_store(name: str, store) -> np.ndarray:
    if name in store:
        return store[name]
    tagged = name + BF16_TAG
    if tagged in store:
        import ml_dtypes
        return store[tagged].view(ml_dtypes.bfloat16)
    raise NotFoundError(f"{name!r} missing from saved store")


def save_vars(executor: Optional[Executor], dirname: str,
              main_program: Optional[Program] = None,
              vars: Optional[Sequence[Variable]] = None,
              predicate=None, filename: Optional[str] = None,
              scope: Optional[Scope] = None,
              save_as_bf16: bool = False):
    """≙ fluid.io.save_vars (reference io.py:89). Values come from the scope
    (device arrays are fetched to host)."""
    program = main_program or default_main_program()
    scope = scope or global_scope()
    if vars is None:
        enforce(predicate is not None, "need vars or predicate",
                exc=InvalidArgumentError)
        vars = _select_vars(program, predicate)
    os.makedirs(dirname, exist_ok=True)
    arrays: Dict[str, np.ndarray] = {}
    for v in vars:
        if not scope.has_var(v.name):
            raise NotFoundError(
                f"variable {v.name!r} not found in scope — run the startup "
                f"program before saving")
        arrays[v.name] = _maybe_bf16(as_numpy(scope.get(v.name)),
                                     save_as_bf16)
    if filename is not None and filename.endswith(".pts"):
        # native C++ tensor container (≙ save_combine_op.cc): streamed,
        # CRC-checked, O(1) name lookup — the fast path for big checkpoints
        from .data.tensor_store import save_tensors
        save_tensors(os.path.join(dirname, filename), arrays)
        return sorted(arrays)
    encoded = dict(_encode_for_npy(n, a) for n, a in arrays.items())
    if filename is None:
        for name, arr in encoded.items():
            np.save(os.path.join(dirname, name + ".npy"), arr)
    else:
        np.savez(os.path.join(dirname, filename), **encoded)
    return sorted(arrays)


def load_vars(executor: Optional[Executor], dirname: str,
              main_program: Optional[Program] = None,
              vars: Optional[Sequence[Variable]] = None,
              predicate=None, filename: Optional[str] = None,
              scope: Optional[Scope] = None):
    """≙ fluid.io.load_vars (reference io.py:317)."""
    program = main_program or default_main_program()
    scope = scope or global_scope()
    if vars is None:
        enforce(predicate is not None, "need vars or predicate",
                exc=InvalidArgumentError)
        vars = _select_vars(program, predicate)
    if filename is not None and filename.endswith(".pts"):
        from .data.tensor_store import load_tensors
        store = load_tensors(os.path.join(dirname, filename),
                             [v.name for v in vars])
        decode_native = True
    elif filename is not None:
        path = os.path.join(dirname, filename)
        with np.load(path) as data:
            store = {k: data[k] for k in data.files}
        decode_native = False
    else:
        store = None
        decode_native = False
    import jax.numpy as jnp
    loaded = []
    for v in vars:
        if store is not None:
            arr = (store[v.name] if decode_native
                   else _decode_from_store(v.name, store))
        else:
            path = os.path.join(dirname, v.name + ".npy")
            tagged = os.path.join(dirname, v.name + BF16_TAG + ".npy")
            if os.path.exists(path):
                arr = np.load(path)
            elif os.path.exists(tagged):
                import ml_dtypes
                arr = np.load(tagged).view(ml_dtypes.bfloat16)
            else:
                raise NotFoundError(f"{path} does not exist")
        if v.shape is not None and -1 not in v.shape:
            enforce(tuple(arr.shape) == tuple(v.shape),
                    f"shape mismatch loading {v.name!r}: file {arr.shape} "
                    f"vs var {v.shape}", exc=InvalidArgumentError)
        target_dtype = np.dtype(v.dtype) if not hasattr(v.dtype, "name") \
            else v.dtype
        val = jnp.asarray(arr)
        if str(val.dtype) != str(np.dtype(target_dtype)):
            val = val.astype(target_dtype)
        scope.set_var(v.name, val)
        loaded.append(v.name)
    return sorted(loaded)


def save_params(executor=None, dirname: str = "", main_program=None,
                filename=None, scope=None, save_as_bf16=False):
    """≙ fluid.io.save_params — trainable parameters only."""
    return save_vars(executor, dirname, main_program=main_program,
                     predicate=_is_parameter, filename=filename, scope=scope,
                     save_as_bf16=save_as_bf16)


def load_params(executor=None, dirname: str = "", main_program=None,
                filename=None, scope=None):
    return load_vars(executor, dirname, main_program=main_program,
                     predicate=_is_parameter, filename=filename, scope=scope)


def save_persistables(executor=None, dirname: str = "", main_program=None,
                      filename=None, scope=None, save_as_bf16=False,
                      sharded: bool = False):
    """≙ fluid.io.save_persistables (reference io.py:252) — parameters AND
    optimizer state/moving stats, i.e. everything needed to resume.

    sharded=True: each process writes only its addressable shards plus a
    manifest (sharded_checkpoint.save_sharded) — ZeRO-1/EP state that does
    not fit one host checkpoints without a gather, and restore can re-shard
    onto a different mesh (≙ SURVEY §5 "jittable sharded checkpoint
    (tensorstore-style)"; reference trainer.py:641 per-shard pserver
    checkpoints)."""
    if sharded:
        from .sharded_checkpoint import save_sharded
        enforce(filename is None and not save_as_bf16,
                "sharded=True does not combine with filename/save_as_bf16 "
                "(shards go to per-process .pts containers in the native "
                "dtypes of the arrays)", exc=InvalidArgumentError)
        program = main_program or default_main_program()
        scope = scope or global_scope()
        vars = _select_vars(program, _is_persistable)
        arrays = {}
        for v in vars:
            if not scope.has_var(v.name):
                raise NotFoundError(
                    f"variable {v.name!r} not found in scope — run the "
                    f"startup program before saving")
            arrays[v.name] = scope.get(v.name)
        save_sharded(dirname, arrays)
        return sorted(arrays)
    return save_vars(executor, dirname, main_program=main_program,
                     predicate=_is_persistable, filename=filename,
                     scope=scope, save_as_bf16=save_as_bf16)


def load_persistables(executor=None, dirname: str = "", main_program=None,
                      filename=None, scope=None, sharded: bool = False,
                      shardings=None):
    """sharded=True restores from a sharded_checkpoint directory;
    `shardings` optionally maps var name -> jax Sharding to re-shard onto
    the CURRENT mesh (unlisted vars restore as host-resident arrays)."""
    if sharded:
        from .sharded_checkpoint import restore_sharded
        enforce(filename is None, "sharded=True does not combine with "
                "filename", exc=InvalidArgumentError)
        program = main_program or default_main_program()
        scope = scope or global_scope()
        vars = _select_vars(program, _is_persistable)
        restored = restore_sharded(dirname, shardings=shardings,
                                   names=[v.name for v in vars])
        for name, val in restored.items():
            scope.set_var(name, val)
        return sorted(restored)
    return load_vars(executor, dirname, main_program=main_program,
                     predicate=_is_persistable, filename=filename,
                     scope=scope)


def save_inference_model(dirname: str,
                         feeded_var_names: Sequence[str],
                         target_vars: Sequence[Variable],
                         executor: Optional[Executor] = None,
                         main_program: Optional[Program] = None,
                         model_filename: Optional[str] = None,
                         params_filename: Optional[str] = None,
                         scope: Optional[Scope] = None,
                         save_as_bf16: bool = False,
                         export: bool = False,
                         native: bool = False):
    """≙ fluid.io.save_inference_model (reference io.py:561): prune the
    program to the fetch targets, switch to test mode, serialize program +
    parameters. With export=True additionally emits a serialized
    jax.export/StableHLO artifact (see export_inference_model) that serves
    cold without the tracer."""
    program = main_program or default_main_program()
    scope = scope or global_scope()
    target_names = [t.name if isinstance(t, Variable) else t
                    for t in target_vars]
    inference_program = program.clone(for_test=True).prune(target_names)
    blk = inference_program.global_block()
    for name in feeded_var_names:
        enforce(blk.has_var(name),
                f"feeded var {name!r} not present in pruned program "
                f"(not on the path to targets?)", exc=InvalidArgumentError)

    os.makedirs(dirname, exist_ok=True)
    meta = {
        "program": json.loads(inference_program.to_json()),
        "feed_names": list(feeded_var_names),
        "fetch_names": target_names,
    }
    with open(os.path.join(dirname, model_filename or
                           INFERENCE_PROGRAM_FILE), "w") as f:
        json.dump(meta, f)

    persistables = _select_vars(inference_program, _is_persistable)
    save_vars(executor, dirname, main_program=inference_program,
              vars=persistables,
              filename=params_filename or PARAMS_COMBINED_FILE, scope=scope,
              save_as_bf16=save_as_bf16)
    if export:
        export_inference_model(dirname, feeded_var_names, target_names,
                               inference_program, scope=scope,
                               native=native)
    return target_names


def load_inference_model(dirname: str,
                         executor: Optional[Executor] = None,
                         model_filename: Optional[str] = None,
                         params_filename: Optional[str] = None,
                         scope: Optional[Scope] = None):
    """≙ fluid.io.load_inference_model (reference io.py:677).
    Returns (program, feed_names, fetch_names); parameters are loaded into
    the scope."""
    scope = scope or global_scope()
    path = os.path.join(dirname, model_filename or INFERENCE_PROGRAM_FILE)
    if not os.path.exists(path):
        raise NotFoundError(f"no inference model at {path}")
    with open(path) as f:
        meta = json.load(f)
    program = Program.from_json(json.dumps(meta["program"]))
    persistables = _select_vars(program, _is_persistable)
    load_vars(executor, dirname, main_program=program, vars=persistables,
              filename=params_filename or PARAMS_COMBINED_FILE, scope=scope)
    return program, list(meta["feed_names"]), list(meta["fetch_names"])


EXPORTED_ARTIFACT_FILE = "__exported__.bin"
EXPORTED_META_FILE = "__exported__.json"
NATIVE_ARTIFACT_FILE = "__exported_native__.stablehlo"
NATIVE_META_FILE = "__exported_native__.meta"


def export_inference_model(dirname: str,
                           feeded_var_names: Sequence[str],
                           target_names: Sequence[str],
                           inference_program: Program,
                           scope: Optional[Scope] = None,
                           platforms: Sequence[str] = ("cpu", "tpu"),
                           native: bool = False):
    """Emit a serialized jax.export (StableHLO) artifact next to the JSON
    program: the whole pruned inference function — parameters baked in as
    constants — in a form a serving process loads and calls COLD, with no
    program tracer, no op registry, and no model-building code.

    ≙ the reference's C++-loadable serving artifact
    (inference/api/paddle_inference_api.h:1 + api_impl.cc:126 +
    inference/io.cc LoadInferenceModel): its ProgramDesc+params directory is
    what a C++ server consumes; here the equivalent deployable unit is
    serialized StableHLO, which any PJRT runtime (tpu serving, CPU) can
    execute. Leading -1 dims export as symbolic so one artifact serves any
    batch size.

    Feed contract: every feed's leading -1 dimension is bound to ONE
    shared batch symbol — all dynamic-leading feeds of one artifact must
    arrive with equal first dims (a sequence var and its @SEQLEN lengths,
    an image and its label, ...). A feed whose dynamic leading dim is NOT
    the batch (e.g. a variable-row auxiliary table) must be exported with
    that dim concrete, or through a separate artifact; jax.export shape
    refinement rejects unequal leading dims at call time (the Predictor
    surfaces this as a shape-refinement error naming the symbol 'b').
    """
    import jax
    import jax.numpy as jnp
    from jax import export as jax_export

    from .framework.lowering import build_plan, run_plan
    from .framework.registry import LowerCtx

    scope = scope or global_scope()
    block = inference_program.global_block()
    plan = build_plan(block)
    feed_names = list(feeded_var_names)
    target_names = list(target_names)

    read = set()
    for op in block.ops:
        read |= set(op.input_names())
    state_names = sorted(n for n in read
                         if scope.has_var(n) and n not in feed_names)
    # fetched to host once; embedded as constants in the artifact
    state_vals = {n: np.asarray(as_numpy(scope.get(n)))
                  for n in state_names}

    def fn(*feeds):
        env: Dict[str, object] = dict(state_vals)
        env.update(zip(feed_names, feeds))
        # extras['program'] lets control-flow ops (static_rnn/while/cond)
        # resolve their sub-blocks — a beam-search decode graph exports
        # the same way a feed-forward one does
        ctx = LowerCtx(rng_key=jax.random.PRNGKey(0), is_test=True,
                       extras={"program": inference_program,
                               "fetch_names": tuple(target_names)})
        run_plan(plan, env, block, ctx)
        return tuple(env[n] for n in target_names)

    sym_scope = jax_export.SymbolicScope()
    args = []
    for i, name in enumerate(feed_names):
        v = block.var(name)
        # every feed's leading -1 is the SAME batch symbol: feeds share
        # the batch by the feed contract, and computations between them
        # (e.g. a sequence var and its @SEQLEN lengths) must broadcast
        dims = [("b" if j == 0 else f"d{i}_{j}") if d == -1 else str(d)
                for j, d in enumerate(v.shape)]
        shape = jax_export.symbolic_shape(", ".join(dims), scope=sym_scope) \
            if any(d == -1 for d in v.shape) else tuple(v.shape)
        args.append(jax.ShapeDtypeStruct(shape, jnp.dtype(v.dtype)))

    exported = jax_export.export(jax.jit(fn), platforms=tuple(platforms))(
        *args)
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, EXPORTED_ARTIFACT_FILE), "wb") as f:
        f.write(exported.serialize())
    with open(os.path.join(dirname, EXPORTED_META_FILE), "w") as f:
        json.dump({"feed_names": feed_names, "fetch_names": target_names,
                   "platforms": list(platforms)}, f)

    if not native:
        return
    # native (C++) serving artifact: a SINGLE-platform cpu export whose raw
    # StableHLO bytecode a C++ process executes directly (native/
    # ptpu_predict.cc) — no Python, no tracer, no op registry. ≙ the
    # reference's C++-loadable predictor unit
    # (inference/api/paddle_inference_api.h:1, api_impl.cc:126). Single
    # platform keeps main() free of the platform_index argument.
    native = jax_export.export(jax.jit(fn), platforms=("cpu",))(*args)
    with open(os.path.join(dirname, NATIVE_ARTIFACT_FILE), "wb") as f:
        f.write(native.mlir_module_serialized)

    def _dims(aval):
        return " ".join(str(d) if isinstance(d, int) else "-1"
                        for d in aval.shape)

    kept = list(native.module_kept_var_idx)
    lines = [f"version {native.calling_convention_version}",
             f"nin {len(kept)}"]
    for i in kept:
        aval = native.in_avals[i]
        lines.append(f"in {feed_names[i]} {aval.dtype} {_dims(aval)}".rstrip())
    lines.append(f"nout {len(native.out_avals)}")
    for name, aval in zip(target_names, native.out_avals):
        lines.append(f"out {name} {aval.dtype} {_dims(aval)}".rstrip())
    with open(os.path.join(dirname, NATIVE_META_FILE), "w") as f:
        f.write("\n".join(lines) + "\n")


def load_exported_model(dirname: str):
    """Deserialize a jax.export artifact written by export_inference_model.
    Returns (exported, feed_names, fetch_names); `exported.call(*feeds)`
    runs it — no program, no registry, no tracer."""
    from jax import export as jax_export
    path = os.path.join(dirname, EXPORTED_ARTIFACT_FILE)
    if not os.path.exists(path):
        raise NotFoundError(f"no exported artifact at {path}")
    with open(path, "rb") as f:
        exported = jax_export.deserialize(bytearray(f.read()))
    with open(os.path.join(dirname, EXPORTED_META_FILE)) as f:
        meta = json.load(f)
    return exported, list(meta["feed_names"]), list(meta["fetch_names"])


NATIVE_TRAIN_ARTIFACT_FILE = "__exported_train__.stablehlo"
NATIVE_TRAIN_META_FILE = "__exported_train__.meta"


def export_train_program(dirname: str,
                         feeded_var_names: Sequence[str],
                         loss_names: Sequence,
                         main_program: Optional[Program] = None,
                         scope: Optional[Scope] = None):
    """Export ONE TRAIN STEP as a C++-executable StableHLO artifact:
    (seed, batch..., params/state...) -> (losses..., updated state...).

    ≙ the reference's pure-C++ training demo input (reference
    train/demo/demo_trainer.cc:55-80: load a serialized ProgramDesc, loop
    executor.Run). Where the reference C++ interprets the program op by op,
    the TPU-native deployable unit is the fully-compiled train step:
    parameters and optimizer accumulators are real ARGUMENTS (not baked
    constants like the inference export), so a C++ driver
    (native/ptpu_train.cc) carries the updated state across steps with no
    Python in the process.

    The meta file records, per kept input/output, name/dtype/dims, plus:
      carry <out_idx> <in_idx>  — output to feed back as input next step
      init <in_idx> <file.npy>  — initial value for a state input
    The first input is always the int32 scalar `__seed__` (the step's RNG
    seed; drives dropout etc.).
    """
    import jax
    import jax.numpy as jnp
    from jax import export as jax_export

    from .framework.executor import Executor
    from .framework.lowering import build_plan, run_plan
    from .framework.registry import LowerCtx

    program = main_program or default_main_program()
    scope = scope or global_scope()
    block = program.global_block()
    plan = build_plan(block)
    feed_names = list(feeded_var_names)
    fetch_names = [f.name if isinstance(f, Variable) else f
                   for f in loss_names]

    ro, rw, out_only = Executor()._analyze_state(program, scope, feed_names,
                                                 fetch_names)
    state_in = list(ro) + list(rw)
    state_out = sorted(set(rw) | set(out_only))

    def fn(seed, *args):
        feeds = args[:len(feed_names)]
        states = args[len(feed_names):]
        ctx = LowerCtx(rng_key=jax.random.PRNGKey(seed),
                       extras={"program": program,
                               "fetch_names": tuple(fetch_names)})
        env: Dict[str, object] = {}
        env.update(zip(state_in, states))
        env.update(zip(feed_names, feeds))
        run_plan(plan, env, block, ctx)
        return (tuple(env[n] for n in fetch_names)
                + tuple(env[n] for n in state_out))

    sym_scope = jax_export.SymbolicScope()
    args = [jax.ShapeDtypeStruct((), jnp.int32)]
    in_names = ["__seed__"]
    for i, name in enumerate(feed_names):
        v = block.var(name)
        dt = jax.dtypes.canonicalize_dtype(np.dtype(v.dtype))
        # the leading -1 is THE batch dim: one shared symbol across all
        # feeds (x and its labels must agree or any x-vs-label op fails)
        dims = [("b" if j == 0 else f"d{i}_{j}") if d == -1 else str(d)
                for j, d in enumerate(v.shape)]
        shape = jax_export.symbolic_shape(", ".join(dims), scope=sym_scope) \
            if any(d == -1 for d in v.shape) else tuple(v.shape)
        args.append(jax.ShapeDtypeStruct(shape, dt))
        in_names.append(name)
    init_vals = {}
    for name in state_in:
        val = np.asarray(as_numpy(scope.get(name)))
        dt = jax.dtypes.canonicalize_dtype(val.dtype)
        args.append(jax.ShapeDtypeStruct(val.shape, dt))
        in_names.append(name)
        init_vals[name] = val.astype(dt)

    exported = jax_export.export(jax.jit(fn), platforms=("cpu",))(*args)
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, NATIVE_TRAIN_ARTIFACT_FILE), "wb") as f:
        f.write(exported.mlir_module_serialized)

    def _dims(aval):
        return " ".join(str(d) if isinstance(d, int) else "-1"
                        for d in aval.shape)

    kept = list(exported.module_kept_var_idx)
    out_names = fetch_names + state_out
    lines = [f"version {exported.calling_convention_version}",
             f"nfetch {len(fetch_names)}"]
    kept_names = []
    for i in kept:
        aval = exported.in_avals[i]
        nm = in_names[i]
        kept_names.append(nm)
        lines.append(f"in {nm} {aval.dtype} {_dims(aval)}".rstrip())
    for nm, aval in zip(out_names, exported.out_avals):
        lines.append(f"out {nm} {aval.dtype} {_dims(aval)}".rstrip())
    for out_idx, nm in enumerate(out_names):
        if nm in state_out and nm in rw and nm in kept_names:
            lines.append(f"carry {out_idx} {kept_names.index(nm)}")
    for in_idx, nm in enumerate(kept_names):
        if nm in init_vals:
            fname = f"train_state_{in_idx}.npy"
            np.save(os.path.join(dirname, fname), init_vals[nm])
            lines.append(f"init {in_idx} {fname}")
    with open(os.path.join(dirname, NATIVE_TRAIN_META_FILE), "w") as f:
        f.write("\n".join(lines) + "\n")
    return fetch_names


TRAIN_PROGRAM_FILE = "__train_program__"


def save_program(dirname: str,
                 main_program: Optional[Program] = None,
                 startup_program: Optional[Program] = None,
                 feed_names: Optional[Sequence[str]] = None,
                 fetch_names: Optional[Sequence] = None):
    """Serialize a TRAINING program pair (main + startup) so a driver with
    no model-building code can train it (≙ the reference's C++ demo
    trainer input: a saved ProgramDesc consumed by train/demo/
    demo_trainer.cc:55-80). Parameters are NOT saved — the startup program
    initializes them, exactly as in the reference demo."""
    from .framework.program import default_startup_program
    main_program = main_program or default_main_program()
    startup_program = startup_program or default_startup_program()
    os.makedirs(dirname, exist_ok=True)
    meta = {
        "main_program": json.loads(main_program.to_json()),
        "startup_program": json.loads(startup_program.to_json()),
        "feed_names": list(feed_names or []),
        "fetch_names": [f.name if isinstance(f, Variable) else f
                        for f in (fetch_names or [])],
    }
    with open(os.path.join(dirname, TRAIN_PROGRAM_FILE), "w") as f:
        json.dump(meta, f)


def load_program(dirname: str):
    """Load a program pair saved by save_program. Returns
    (main_program, startup_program, feed_names, fetch_names)."""
    path = os.path.join(dirname, TRAIN_PROGRAM_FILE)
    if not os.path.exists(path):
        raise NotFoundError(f"no saved training program at {path}")
    with open(path) as f:
        meta = json.load(f)
    return (Program.from_json(json.dumps(meta["main_program"])),
            Program.from_json(json.dumps(meta["startup_program"])),
            meta["feed_names"], meta["fetch_names"])
