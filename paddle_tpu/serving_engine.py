"""Compat shim: the continuous-batching engine moved into the serving
package (`paddle_tpu.serving.engine`, ISSUE r20 — the paged KV-cache
subsystem promoted `serving_engine.py`/`serving.py` into
`paddle_tpu/serving/`). Import from `paddle_tpu.serving` going forward;
this module keeps the historical `paddle_tpu.serving_engine` path alive
for existing callers (tests, tools, operator muscle memory)."""

from __future__ import annotations

from .serving.engine import (  # noqa: F401
    ContinuousBatchingEngine,
    EngineClient,
    EngineServer,
    GenRequest,
    SlotAllocator,
    _MetricsHTTPServer,
    scrape_healthz,
    scrape_metrics,
)
from .serving.kv_pager import PagedKVEngine  # noqa: F401
