"""Operator-overload dispatch + scale layer.

≙ reference python/paddle/fluid/layers/math_op_patch.py (monkey-patched
Variable arithmetic) — here Variable calls into this module directly.
"""

from __future__ import annotations

import numbers

from ..core.dtypes import dtype_name
from ..layer_helper import LayerHelper

_COMPARE_OPS = {"less_than", "less_equal", "greater_than", "greater_equal",
                "equal", "not_equal"}


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", name=name, act=act)
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype), shape=x.shape)
    helper.append_op(type="scale", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"scale": float(scale), "bias": float(bias),
                            "bias_after_scale": bias_after_scale})
    return helper.append_activation(out)


def _fill_like_scalar(x, value):
    from . import tensor as tensor_layers
    return tensor_layers.fill_constant(shape=[1], dtype=dtype_name(x.dtype),
                                       value=float(value))


def _broadcast_shape(sa, sb):
    """Declared shape of a trailing-aligned elementwise result. The old
    rule ("higher-rank operand wins") under-declared broadcast dims of the
    equal-rank case — e.g. [1, 1, T] < [S, 1, 1] really yields [S, 1, T] —
    which the static analyzer (framework/analysis.py) flags as a
    declared-shape lie. -1 (batch) dims broadcast like any size but stay
    symbolic in the result."""
    if not sa or not sb:
        return sa if sa else sb
    ra, rb = len(sa), len(sb)
    out = []
    for i in range(max(ra, rb)):
        da = sa[ra - 1 - i] if i < ra else 1
        db = sb[rb - 1 - i] if i < rb else 1
        if da == db:
            out.append(da)
        elif da == 1:
            out.append(db)
        elif db == 1:
            out.append(da)
        elif -1 in (da, db):
            out.append(-1)
        else:
            out.append(da)    # incompatible: runtime raises; keep a's view
    out.reverse()
    return tuple(out)


def elementwise_binary_dispatch(x, other, op_type, reverse=False):
    """Implements Variable.__add__ & co."""
    if isinstance(other, numbers.Number):
        if op_type in _COMPARE_OPS:
            other = _fill_like_scalar(x, other)
        elif not reverse:
            if op_type == "elementwise_add":
                return scale(x, 1.0, float(other))
            if op_type == "elementwise_sub":
                return scale(x, 1.0, -float(other))
            if op_type == "elementwise_mul":
                return scale(x, float(other))
            if op_type == "elementwise_div":
                return scale(x, 1.0 / float(other))
            other = _fill_like_scalar(x, other)
        else:
            if op_type == "elementwise_sub":  # other - x
                return scale(x, -1.0, float(other))
            other = _fill_like_scalar(x, other)
    a, b = (other, x) if reverse else (x, other)
    helper = LayerHelper(op_type)
    out_dtype = "bool" if op_type in _COMPARE_OPS else dtype_name(a.dtype)
    shape = _broadcast_shape(a.shape, b.shape)
    out = helper.create_tmp_variable(dtype=out_dtype, shape=shape,
                                     stop_gradient=op_type in _COMPARE_OPS)
    helper.append_op(type=op_type, inputs={"X": [a], "Y": [b]},
                     outputs={"Out": [out]}, attrs={"axis": -1})
    return out
