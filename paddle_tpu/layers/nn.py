"""Neural-network layers.

≙ reference python/paddle/fluid/layers/nn.py (79 layers: fc:114,
embedding:226, conv2d:1369, batch_norm:2004, layer_norm:2155, ...). Each layer
creates parameters via LayerHelper and appends ops; the TPU executor traces
and XLA-compiles the resulting program.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

from ..core.dtypes import dtype_name
from ..core.enforce import InvalidArgumentError, enforce
from ..framework.program import Variable
from ..initializer import ConstantInitializer, NormalInitializer
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr


def _prod(xs):
    out = 1
    for x in xs:
        out *= x
    return out


# ---------------------------------------------------------------- fc
def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, is_test=False, name=None, use_bf16=False):
    """Fully connected layer (≙ reference layers/nn.py:114).

    use_bf16 routes the matmul through bfloat16 on the MXU with fp32
    accumulation (TPU-native analogue of fp16 kernels)."""
    helper = LayerHelper("fc", name=name, act=act, bias_attr=bias_attr)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    param_attrs = param_attr if isinstance(param_attr, (list, tuple)) \
        else [param_attr] * len(inputs)
    mul_results = []
    for inp, pattr in zip(inputs, param_attrs):
        in_dim = _prod(inp.shape[num_flatten_dims:])
        w = helper.create_parameter(pattr, shape=[in_dim, size],
                                    dtype=dtype_name(inp.dtype))
        out_shape = list(inp.shape[:num_flatten_dims]) + [size]
        tmp = helper.create_tmp_variable(dtype=dtype_name(inp.dtype),
                                         shape=out_shape)
        helper.append_op(type="mul", inputs={"X": [inp], "Y": [w]},
                         outputs={"Out": [tmp]},
                         attrs={"x_num_col_dims": num_flatten_dims,
                                "y_num_col_dims": 1, "use_bf16": use_bf16})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_tmp_variable(
            dtype=dtype_name(inputs[0].dtype), shape=mul_results[0].shape)
        helper.append_op(type="sum", inputs={"X": mul_results},
                         outputs={"Out": [pre_bias]})
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims,
                                    use_bf16=use_bf16)
    return helper.append_activation(pre_act)


# ---------------------------------------------------------------- embedding
def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """≙ reference layers/nn.py:226 + lookup_table_op.cc:21. On TPU the table
    is a dense (shardable) array; is_sparse/is_distributed accepted for API
    parity — sharding is configured via the parallel strategy instead."""
    helper = LayerHelper("embedding", name=None)
    w = helper.create_parameter(param_attr, shape=list(size), dtype=dtype,
                                default_initializer=NormalInitializer(0., 0.02))
    in_shape = list(input.shape)
    if in_shape and in_shape[-1] == 1:
        in_shape = in_shape[:-1]
    out = helper.create_tmp_variable(dtype=dtype,
                                     shape=in_shape + [size[1]])
    helper.append_op(type="lookup_table",
                     inputs={"W": [w], "Ids": [input]},
                     outputs={"Out": [out]},
                     attrs={"is_sparse": is_sparse,
                            "is_distributed": is_distributed,
                            "padding_idx": padding_idx})
    return out


# ---------------------------------------------------------------- conv
def _pair(x):
    return list(x) if isinstance(x, (list, tuple)) else [x, x]


def _conv_out_dim(in_dim, k, pad, stride, dilation=1):
    if in_dim == -1:
        return -1
    return (in_dim + 2 * pad - (dilation * (k - 1) + 1)) // stride + 1


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           use_cudnn=True, name=None, data_format="NCHW", use_bf16=False):
    """≙ reference layers/nn.py:1369 (conv2d). use_cudnn accepted for API
    parity and ignored — XLA picks the conv implementation."""
    helper = LayerHelper("conv2d", name=name, act=act, bias_attr=bias_attr)
    filter_size = _pair(filter_size)
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    groups = groups or 1
    c_axis = 1 if data_format == "NCHW" else 3
    num_channels = input.shape[c_axis]
    w_shape = [num_filters, num_channels // groups] + filter_size
    fan_in = (num_channels // groups) * filter_size[0] * filter_size[1]
    std = (2.0 / fan_in) ** 0.5
    w = helper.create_parameter(param_attr, shape=w_shape,
                                dtype=dtype_name(input.dtype),
                                default_initializer=NormalInitializer(0., std))
    if data_format == "NCHW":
        n, c, h, wd = input.shape
        out_shape = [n, num_filters,
                     _conv_out_dim(h, filter_size[0], padding[0], stride[0],
                                   dilation[0]),
                     _conv_out_dim(wd, filter_size[1], padding[1], stride[1],
                                   dilation[1])]
    else:
        n, h, wd, c = input.shape
        out_shape = [n,
                     _conv_out_dim(h, filter_size[0], padding[0], stride[0],
                                   dilation[0]),
                     _conv_out_dim(wd, filter_size[1], padding[1], stride[1],
                                   dilation[1]),
                     num_filters]
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=out_shape)
    helper.append_op(type="conv2d",
                     inputs={"Input": [input], "Filter": [w]},
                     outputs={"Output": [out]},
                     attrs={"strides": stride, "paddings": padding,
                            "dilations": dilation, "groups": groups,
                            "data_format": data_format, "use_bf16": use_bf16})
    pre_act = helper.append_bias_op(out, dim_start=c_axis,
                                    dim_end=c_axis + 1, use_bf16=use_bf16)
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, filter_size=None, output_size=None,
                     stride=1, padding=0, dilation=1, param_attr=None,
                     bias_attr=None, act=None, name=None):
    """≙ reference layers/nn.py conv2d_transpose."""
    helper = LayerHelper("conv2d_transpose", name=name, act=act,
                         bias_attr=bias_attr)
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    n, c, h, wd = input.shape
    if filter_size is None:
        enforce(output_size is not None,
                "need filter_size or output_size", exc=InvalidArgumentError)
        output_size = _pair(output_size)
        filter_size = [output_size[0] - (h - 1) * stride[0] + 2 * padding[0],
                       output_size[1] - (wd - 1) * stride[1] + 2 * padding[1]]
    else:
        filter_size = _pair(filter_size)
    w = helper.create_parameter(param_attr,
                                shape=[c, num_filters] + filter_size,
                                dtype=dtype_name(input.dtype))

    def _out(in_dim, k, pad, s):
        return -1 if in_dim == -1 else (in_dim - 1) * s - 2 * pad + k

    out_shape = [n, num_filters,
                 _out(h, filter_size[0], padding[0], stride[0]),
                 _out(wd, filter_size[1], padding[1], stride[1])]
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=out_shape)
    helper.append_op(type="conv2d_transpose",
                     inputs={"Input": [input], "Filter": [w]},
                     outputs={"Output": [out]},
                     attrs={"strides": stride, "paddings": padding,
                            "dilations": dilation})
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


# ---------------------------------------------------------------- pool
def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           exclusive=True, use_cudnn=True, name=None, data_format="NCHW"):
    """≙ reference layers/nn.py pool2d."""
    helper = LayerHelper("pool2d", name=name)
    pool_size = _pair(pool_size)
    pool_stride = _pair(pool_stride)
    pool_padding = _pair(pool_padding)
    spatial = (2, 3) if data_format == "NCHW" else (1, 2)
    out_shape = list(input.shape)
    for i, d in enumerate(spatial):
        if global_pooling:
            out_shape[d] = 1
        elif out_shape[d] != -1:
            span = out_shape[d] + 2 * pool_padding[i] - pool_size[i]
            if ceil_mode:
                out_shape[d] = -(-span // pool_stride[i]) + 1
            else:
                out_shape[d] = span // pool_stride[i] + 1
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=out_shape)
    helper.append_op(type="pool2d", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"pooling_type": pool_type, "ksize": pool_size,
                            "strides": pool_stride, "paddings": pool_padding,
                            "global_pooling": global_pooling,
                            "exclusive": exclusive, "ceil_mode": ceil_mode,
                            "data_format": data_format})
    return out


def _triple(x):
    return list(x) if isinstance(x, (list, tuple)) else [x, x, x]


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           use_cudnn=True, name=None, data_format="NCDHW", use_bf16=False):
    """≙ reference layers/nn.py conv3d (conv_op.cc vol2col path). Input
    [N, C, D, H, W] (or NDHWC); filter [M, C/g, kd, kh, kw]."""
    helper = LayerHelper("conv3d", name=name, act=act, bias_attr=bias_attr)
    filter_size = _triple(filter_size)
    stride = _triple(stride)
    padding = _triple(padding)
    dilation = _triple(dilation)
    groups = groups or 1
    c_axis = 1 if data_format == "NCDHW" else 4
    num_channels = input.shape[c_axis]
    w_shape = [num_filters, num_channels // groups] + filter_size
    fan_in = (num_channels // groups) * int(
        filter_size[0] * filter_size[1] * filter_size[2])
    std = (2.0 / fan_in) ** 0.5
    w = helper.create_parameter(param_attr, shape=w_shape,
                                dtype=dtype_name(input.dtype),
                                default_initializer=NormalInitializer(0., std))
    spatial_in = (input.shape[2:5] if data_format == "NCDHW"
                  else input.shape[1:4])
    spatial_out = [_conv_out_dim(s, filter_size[i], padding[i], stride[i],
                                 dilation[i])
                   for i, s in enumerate(spatial_in)]
    if data_format == "NCDHW":
        out_shape = [input.shape[0], num_filters] + spatial_out
    else:
        out_shape = [input.shape[0]] + spatial_out + [num_filters]
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=out_shape)
    helper.append_op(type="conv3d",
                     inputs={"Input": [input], "Filter": [w]},
                     outputs={"Output": [out]},
                     attrs={"strides": stride, "paddings": padding,
                            "dilations": dilation, "groups": groups,
                            "data_format": data_format, "use_bf16": use_bf16})
    pre_act = helper.append_bias_op(out, dim_start=c_axis,
                                    dim_end=c_axis + 1, use_bf16=use_bf16)
    return helper.append_activation(pre_act)


def conv3d_transpose(input, num_filters, filter_size=None, output_size=None,
                     stride=1, padding=0, dilation=1, param_attr=None,
                     bias_attr=None, act=None, use_cudnn=True, name=None):
    """≙ reference layers/nn.py conv3d_transpose (conv_transpose_op.cc 3-D
    path). Input [N, C, D, H, W]; filter stored [C, M, kd, kh, kw]."""
    helper = LayerHelper("conv3d_transpose", name=name, act=act,
                         bias_attr=bias_attr)
    stride = _triple(stride)
    padding = _triple(padding)
    dilation = _triple(dilation)
    n, c = input.shape[0], input.shape[1]
    spatial_in = list(input.shape[2:5])
    if filter_size is None:
        enforce(output_size is not None,
                "conv3d_transpose needs filter_size or output_size",
                exc=InvalidArgumentError)
        output_size = _triple(output_size)
        # invert out = (in-1)*s - 2p + d*(k-1) + 1 for k
        filter_size = []
        for i in range(3):
            if spatial_in[i] == -1:
                filter_size.append(1)
                continue
            span = (output_size[i] - (spatial_in[i] - 1) * stride[i]
                    + 2 * padding[i] - 1)
            enforce(span % dilation[i] == 0,
                    f"output_size[{i}]={output_size[i]} unreachable with "
                    f"stride={stride[i]} padding={padding[i]} "
                    f"dilation={dilation[i]}", exc=InvalidArgumentError)
            filter_size.append(span // dilation[i] + 1)
    else:
        filter_size = _triple(filter_size)
    w = helper.create_parameter(param_attr,
                                shape=[c, num_filters] + filter_size,
                                dtype=dtype_name(input.dtype))
    spatial_out = [
        (spatial_in[i] - 1) * stride[i] - 2 * padding[i]
        + dilation[i] * (filter_size[i] - 1) + 1
        if spatial_in[i] != -1 else -1 for i in range(3)]
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=[n, num_filters] + spatial_out)
    helper.append_op(type="conv3d_transpose",
                     inputs={"Input": [input], "Filter": [w]},
                     outputs={"Output": [out]},
                     attrs={"strides": stride, "paddings": padding,
                            "dilations": dilation})
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           exclusive=True, use_cudnn=True, name=None, data_format="NCDHW"):
    """≙ reference layers/nn.py pool3d."""
    helper = LayerHelper("pool3d", name=name)
    pool_size = _triple(pool_size)
    pool_stride = _triple(pool_stride)
    pool_padding = _triple(pool_padding)
    spatial = (2, 3, 4) if data_format == "NCDHW" else (1, 2, 3)
    out_shape = list(input.shape)
    for i, d in enumerate(spatial):
        if global_pooling:
            out_shape[d] = 1
        elif out_shape[d] != -1:
            span = out_shape[d] + 2 * pool_padding[i] - pool_size[i]
            if ceil_mode:
                out_shape[d] = -(-span // pool_stride[i]) + 1
            else:
                out_shape[d] = span // pool_stride[i] + 1
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=out_shape)
    helper.append_op(type="pool3d", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"pooling_type": pool_type, "ksize": pool_size,
                            "strides": pool_stride, "paddings": pool_padding,
                            "global_pooling": global_pooling,
                            "exclusive": exclusive, "ceil_mode": ceil_mode,
                            "data_format": data_format})
    return out


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR"):
    """≙ reference layers/nn.py image_resize (bilinear_interp_op). Input
    [N, C, H, W]; out_shape [H', W'] or scale factor."""
    enforce(resample.upper() == "BILINEAR",
            "only BILINEAR resample is supported", exc=InvalidArgumentError)
    helper = LayerHelper("image_resize", name=name)
    h, w = input.shape[2], input.shape[3]
    if out_shape is None:
        enforce(scale is not None, "image_resize needs out_shape or scale",
                exc=InvalidArgumentError)
        out_h, out_w = int(h * scale), int(w * scale)
        enforce(out_h > 0 and out_w > 0,
                f"image_resize with scale= needs static spatial dims "
                f"(got H={h}, W={w}); pass out_shape for dynamic inputs",
                exc=InvalidArgumentError)
    else:
        out_h, out_w = int(out_shape[0]), int(out_shape[1])
    out = helper.create_tmp_variable(
        dtype=dtype_name(input.dtype),
        shape=[input.shape[0], input.shape[1], out_h, out_w])
    helper.append_op(type="bilinear_interp", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"out_h": out_h, "out_w": out_w})
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None):
    """≙ reference layers/nn.py resize_bilinear."""
    return image_resize(input, out_shape=out_shape, scale=scale, name=name)


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """≙ reference layers/nn.py image_resize_short: resize so the SHORT side
    equals out_short_len, keeping aspect ratio."""
    h, w = input.shape[2], input.shape[3]
    short = min(h, w)
    out_h = int(h * out_short_len / short)
    out_w = int(w * out_short_len / short)
    return image_resize(input, out_shape=[out_h, out_w], resample=resample)


def dice_loss(input, label, epsilon=1e-5):
    """≙ reference layers/nn.py dice_loss: 1 - 2|X∩Y| / (|X|+|Y|).
    input [N, D] probabilities, label [N, 1] int class indices."""

    label = one_hot(label, depth=input.shape[-1])
    reduce_dims = list(range(1, len(input.shape)))
    inse = reduce_sum(input * label, dim=reduce_dims)
    dice_denominator = reduce_sum(input, dim=reduce_dims) + \
        reduce_sum(label, dim=reduce_dims) + epsilon
    dice_score = 1 - inse * 2 / dice_denominator
    return reduce_mean(dice_score)


def positive_negative_pair(score, label, query_id, name=None):
    """≙ reference positive_negative_pair_op.cc: counts of correctly /
    incorrectly / neutrally ranked pairs per query group. Returns
    (positive, negative, neutral) float scalars."""
    helper = LayerHelper("positive_negative_pair", name=name)
    pos = helper.create_tmp_variable(dtype="float32", shape=[1])
    neg = helper.create_tmp_variable(dtype="float32", shape=[1])
    neu = helper.create_tmp_variable(dtype="float32", shape=[1])
    helper.append_op(type="positive_negative_pair",
                     inputs={"Score": [score], "Label": [label],
                             "QueryID": [query_id]},
                     outputs={"PositivePair": [pos], "NegativePair": [neg],
                              "NeutralPair": [neu]})
    return pos, neg, neu


# ---------------------------------------------------------------- norms
def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               name=None, moving_mean_name=None, moving_variance_name=None):
    """≙ reference layers/nn.py:2004. Moving stats are persistable vars
    updated functionally each step."""
    helper = LayerHelper("batch_norm", name=name, act=act)
    c_axis = 1 if data_layout == "NCHW" else input.ndim - 1
    c = input.shape[c_axis]
    dtype = dtype_name(input.dtype)
    scale = helper.create_parameter(param_attr, shape=[c], dtype=dtype,
                                    default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(bias_attr, shape=[c], dtype=dtype,
                                   is_bias=True)
    mean = helper.create_parameter(
        ParamAttr(name=moving_mean_name, trainable=False), shape=[c],
        dtype=dtype, default_initializer=ConstantInitializer(0.0))
    variance = helper.create_parameter(
        ParamAttr(name=moving_variance_name, trainable=False), shape=[c],
        dtype=dtype, default_initializer=ConstantInitializer(1.0))
    mean.stop_gradient = True
    variance.stop_gradient = True
    y = helper.create_tmp_variable(dtype=dtype, shape=input.shape)
    saved_mean = helper.create_tmp_variable(dtype=dtype, shape=[c],
                                            stop_gradient=True)
    saved_var = helper.create_tmp_variable(dtype=dtype, shape=[c],
                                           stop_gradient=True)
    helper.append_op(type="batch_norm",
                     inputs={"X": [input], "Scale": [scale], "Bias": [bias],
                             "Mean": [mean], "Variance": [variance]},
                     outputs={"Y": [y], "MeanOut": [mean],
                              "VarianceOut": [variance],
                              "SavedMean": [saved_mean],
                              "SavedVariance": [saved_var]},
                     attrs={"momentum": momentum, "epsilon": epsilon,
                            "data_layout": data_layout, "is_test": is_test})
    return helper.append_activation(y)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    """≙ reference layers/nn.py:2155."""
    helper = LayerHelper("layer_norm", name=name, act=act)
    dtype = dtype_name(input.dtype)
    norm_shape = [_prod(input.shape[begin_norm_axis:])]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(param_attr, shape=norm_shape, dtype=dtype,
                                    default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(bias_attr, shape=norm_shape, dtype=dtype,
                                    is_bias=True)
        inputs["Bias"] = [b]
    y = helper.create_tmp_variable(dtype=dtype, shape=input.shape)
    mean = helper.create_tmp_variable(dtype=dtype,
                                      shape=input.shape[:begin_norm_axis],
                                      stop_gradient=True)
    var = helper.create_tmp_variable(dtype=dtype,
                                     shape=input.shape[:begin_norm_axis],
                                     stop_gradient=True)
    helper.append_op(type="layer_norm", inputs=inputs,
                     outputs={"Y": [y], "Mean": [mean], "Variance": [var]},
                     attrs={"begin_norm_axis": begin_norm_axis,
                            "epsilon": epsilon})
    return helper.append_activation(y)


# ---------------------------------------------------------------- dropout
def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype), shape=x.shape)
    mask = helper.create_tmp_variable(dtype=dtype_name(x.dtype),
                                      shape=x.shape, stop_gradient=True)
    helper.append_op(type="dropout", inputs={"X": [x]},
                     outputs={"Out": [out], "Mask": [mask]},
                     attrs={"dropout_prob": dropout_prob, "is_test": is_test,
                            "seed": seed or 0,
                            "dropout_implementation": dropout_implementation})
    return out


# ---------------------------------------------------------------- losses
def softmax(input, name=None, axis=-1):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=input.shape)
    helper.append_op(type="softmax", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, return_softmax=False):
    helper = LayerHelper("softmax_with_cross_entropy")
    loss_shape = list(logits.shape[:-1]) + [1]
    loss = helper.create_tmp_variable(dtype=dtype_name(logits.dtype),
                                      shape=loss_shape)
    sm = helper.create_tmp_variable(dtype=dtype_name(logits.dtype),
                                    shape=logits.shape)
    helper.append_op(type="softmax_with_cross_entropy",
                     inputs={"Logits": [logits], "Label": [label]},
                     outputs={"Loss": [loss], "Softmax": [sm]},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index})
    if return_softmax:
        return loss, sm
    return loss


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    loss_shape = list(input.shape[:-1]) + [1]
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=loss_shape)
    helper.append_op(type="cross_entropy",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index})
    return out


def sigmoid_cross_entropy_with_logits(x, label, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype), shape=x.shape)
    helper.append_op(type="sigmoid_cross_entropy_with_logits",
                     inputs={"X": [x], "Label": [label]},
                     outputs={"Out": [out]})
    return out


def square_error_cost(input, label):
    """≙ reference layers/nn.py square_error_cost (fit-a-line loss)."""
    helper = LayerHelper("square_error_cost")
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=input.shape)
    helper.append_op(type="mse_loss", inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out]})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    loss = helper.create_tmp_variable(dtype=dtype_name(x.dtype),
                                      shape=[x.shape[0], 1])
    diff = helper.create_tmp_variable(dtype=dtype_name(x.dtype),
                                      shape=x.shape, stop_gradient=True)
    helper.append_op(type="smooth_l1_loss", inputs=inputs,
                     outputs={"Out": [loss], "Diff": [diff]},
                     attrs={"sigma": sigma or 1.0})
    return loss


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=input.shape)
    resid = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                       shape=input.shape, stop_gradient=True)
    helper.append_op(type="huber_loss",
                     inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out], "Residual": [resid]},
                     attrs={"delta": delta})
    return out


# ---------------------------------------------------------------- reductions
def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype), shape=[])
    helper.append_op(type="mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def _reduce_layer(op_type, input, dim, keep_dim, name):
    helper = LayerHelper(op_type, name=name)
    shape = list(input.shape)
    if dim is None:
        out_shape = [] if not keep_dim else [1] * len(shape)
    else:
        dims = [dim] if isinstance(dim, int) else list(dim)
        dims = [d if d >= 0 else len(shape) + d for d in dims]
        out_shape = [1 if i in dims else d for i, d in enumerate(shape)] \
            if keep_dim else [d for i, d in enumerate(shape)
                              if i not in dims]
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=out_shape)
    helper.append_op(type=op_type, inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"dim": dim, "keep_dim": keep_dim,
                            "reduce_all": dim is None})
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_prod", input, dim, keep_dim, name)


# ---------------------------------------------------------------- manip
def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape", name=name, act=act)
    out_shape = list(shape)
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype),
                                     shape=[d if d != 0 else x.shape[i]
                                            for i, d in enumerate(out_shape)])
    helper.append_op(type="reshape", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"shape": list(shape)})
    return helper.append_activation(out)


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", name=name)
    out = helper.create_tmp_variable(
        dtype=dtype_name(x.dtype),
        shape=[x.shape[p] for p in perm] if x.shape else None)
    helper.append_op(type="transpose", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": list(perm)})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    shape = list(input.shape)
    axis = dim if dim >= 0 else len(shape) + dim
    if isinstance(num_or_sections, int):
        n = num_or_sections
        sections = [shape[axis] // n] * n
        attrs = {"num": n, "axis": axis, "sections": []}
    else:
        sections = list(num_or_sections)
        attrs = {"num": 0, "axis": axis, "sections": sections}
    outs = []
    for s in sections:
        os = list(shape)
        os[axis] = s
        outs.append(helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                               shape=os))
    helper.append_op(type="split", inputs={"X": [input]},
                     outputs={"Out": outs}, attrs=attrs)
    return outs


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze", name=name)
    shape = [d for i, d in enumerate(input.shape) if i not in axes] \
        if input.shape else None
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=shape)
    helper.append_op(type="squeeze", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"axes": list(axes)})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze", name=name)
    shape = list(input.shape)
    for ax in sorted(axes):
        shape.insert(ax, 1)
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=shape)
    helper.append_op(type="unsqueeze", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"axes": list(axes)})
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten", name=name)
    lead = _prod(x.shape[:axis]) if axis > 0 else 1
    trail = _prod(x.shape[axis:])
    if any(d == -1 for d in x.shape[:axis]):
        lead = -1
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype),
                                     shape=[lead, trail])
    helper.append_op(type="flatten", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def stack(x, axis=0):
    helper = LayerHelper("stack")
    xs = x if isinstance(x, (list, tuple)) else [x]
    shape = list(xs[0].shape)
    shape.insert(axis if axis >= 0 else len(shape) + 1 + axis, len(xs))
    out = helper.create_tmp_variable(dtype=dtype_name(xs[0].dtype),
                                     shape=shape)
    helper.append_op(type="stack", inputs={"X": list(xs)},
                     outputs={"Y": [out]}, attrs={"axis": axis})
    return out


def gather(input, index):
    helper = LayerHelper("gather")
    out_shape = list(index.shape) + list(input.shape[1:])
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=out_shape)
    helper.append_op(type="gather",
                     inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]})
    return out


def scatter(input, index, updates, overwrite=True):
    helper = LayerHelper("scatter")
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=input.shape)
    helper.append_op(type="scatter",
                     inputs={"X": [input], "Ids": [index],
                             "Updates": [updates]},
                     outputs={"Out": [out]}, attrs={"overwrite": overwrite})
    return out


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    shape = [(-1 if d == -1 else d * t)
             for d, t in zip(x.shape, expand_times)]
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype), shape=shape)
    helper.append_op(type="expand", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"expand_times": list(expand_times)})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    shape = [(-1 if d == -1 else d + paddings[2 * i] + paddings[2 * i + 1])
             for i, d in enumerate(x.shape)]
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype), shape=shape)
    helper.append_op(type="pad", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"paddings": list(paddings),
                            "pad_value": pad_value})
    return out


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    shape = list(input.shape)
    if shape and shape[-1] == 1:
        shape = shape[:-1]
    out = helper.create_tmp_variable(dtype="float32", shape=shape + [depth],
                                     stop_gradient=True)
    helper.append_op(type="one_hot", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"depth": depth})
    return out


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype), shape=x.shape)
    helper.append_op(type="clip", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"min": min, "max": max})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype), shape=x.shape)
    helper.append_op(type="clip_by_norm", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"max_norm": max_norm})
    return out


# ---------------------------------------------------------------- metrics
def accuracy(input, label, k=1, correct=None, total=None):
    """≙ reference layers/metric_op.py accuracy: top-k then accuracy op."""
    helper = LayerHelper("accuracy")
    topk_out, topk_indices = topk(input, k=k)
    acc = helper.create_tmp_variable(dtype="float32", shape=[],
                                     stop_gradient=True)
    correct = correct or helper.create_tmp_variable(dtype="int32", shape=[],
                                                    stop_gradient=True)
    total = total or helper.create_tmp_variable(dtype="int32", shape=[],
                                                stop_gradient=True)
    helper.append_op(type="accuracy",
                     inputs={"Out": [topk_out], "Indices": [topk_indices],
                             "Label": [label]},
                     outputs={"Accuracy": [acc], "Correct": [correct],
                              "Total": [total]})
    return acc


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    shape = list(input.shape[:-1]) + [k]
    values = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                        shape=shape, stop_gradient=True)
    indices = helper.create_tmp_variable(dtype="int64", shape=shape,
                                         stop_gradient=True)
    helper.append_op(type="top_k", inputs={"X": [input]},
                     outputs={"Out": [values], "Indices": [indices]},
                     attrs={"k": k})
    return values, indices


def auc(input, label, curve="ROC", num_thresholds=200, topk=1):
    """≙ reference layers/metric_op.py auc — streaming AUC with persistable
    bucket state."""
    helper = LayerHelper("auc")
    stat_pos = helper.create_global_variable(
        name=helper.name + ".stat_pos", shape=[num_thresholds + 1],
        dtype="float32")
    stat_neg = helper.create_global_variable(
        name=helper.name + ".stat_neg", shape=[num_thresholds + 1],
        dtype="float32")
    for var in (stat_pos, stat_neg):
        sb = helper.startup_program.global_block()
        if var.name not in sb.vars:
            sv = sb.create_var(name=var.name, shape=var.shape,
                               dtype=var.dtype, persistable=True)
            sb.append_op("fill_constant", outputs={"Out": [sv.name]},
                         attrs={"shape": list(var.shape), "value": 0.0,
                                "dtype": "float32"})
    auc_out = helper.create_tmp_variable(dtype="float32", shape=[],
                                         stop_gradient=True)
    helper.append_op(type="auc",
                     inputs={"Predict": [input], "Label": [label],
                             "StatPos": [stat_pos], "StatNeg": [stat_neg]},
                     outputs={"AUC": [auc_out], "StatPosOut": [stat_pos],
                              "StatNegOut": [stat_neg]},
                     attrs={"num_thresholds": num_thresholds})
    return auc_out, [stat_pos, stat_neg]


# ---------------------------------------------------------------- misc
def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None,
           use_bf16=False):
    helper = LayerHelper("matmul", name=name)
    xs, ys = list(x.shape), list(y.shape)
    if transpose_x:
        xs[-1], xs[-2] = xs[-2], xs[-1]
    if transpose_y:
        ys[-1], ys[-2] = ys[-2], ys[-1]
    batch = xs[:-2] if len(xs) >= len(ys) else ys[:-2]
    out_shape = batch + [xs[-2] if len(xs) > 1 else 1, ys[-1]]
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype),
                                     shape=out_shape)
    helper.append_op(type="matmul", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"transpose_X": transpose_x,
                            "transpose_Y": transpose_y, "alpha": alpha,
                            "use_bf16": use_bf16})
    return out


def elementwise_op_layer(op_type, x, y, axis=-1, act=None, name=None):
    helper = LayerHelper(op_type, name=name, act=act)
    xs, ys = x.shape or (), y.shape or ()
    if len(xs) == len(ys) and all(
            d is not None and d != -1 for d in (*xs, *ys)):
        # equal-rank operands: declare the true numpy broadcast shape
        # (size-1 dims stretch), so e.g. [S,1,1] + [1,G,1] declares
        # [S,G,1] — what the analyzer's inference derives
        shape = [max(a, b) for a, b in zip(xs, ys)]
    else:
        shape = xs if len(xs) >= len(ys) else ys
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype), shape=shape)
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return helper.append_activation(out)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return elementwise_op_layer("elementwise_add", x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return elementwise_op_layer("elementwise_sub", x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return elementwise_op_layer("elementwise_mul", x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return elementwise_op_layer("elementwise_div", x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return elementwise_op_layer("elementwise_max", x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return elementwise_op_layer("elementwise_min", x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return elementwise_op_layer("elementwise_pow", x, y, axis, act, name)


def cache_write(cache, new, pos, axis, batch_axis=None, out=None, name=None):
    """Write `new` (size-1 along `axis`) into `cache` at position `pos` —
    the KV-cache decode primitive (lowers to an in-place
    dynamic_update_slice inside scan carries).

    Default mode: `pos` is one scalar position for the whole batch (any
    tensor; its first element is the position — the contract is enforced).
    With `batch_axis` set, `pos` holds one position PER ROW of `cache`
    along that axis and each row is written at its own position — the
    slot-indexed cache the continuous-batching serving engine runs on.
    `out` (optional Variable) receives the result in place of a fresh
    temporary — pass the cache variable itself to round-trip a persistable
    serving cache through the executor's donated state path."""
    helper = LayerHelper("cache_write", name=name)
    if out is None:
        out = helper.create_tmp_variable(dtype=dtype_name(cache.dtype),
                                         shape=cache.shape,
                                         stop_gradient=True)
    attrs = {"axis": axis}
    if batch_axis is not None:
        attrs["batch_axis"] = batch_axis
    helper.append_op(type="cache_write",
                     inputs={"Cache": [cache], "New": [new], "Pos": [pos]},
                     outputs={"Out": [out]},
                     attrs=attrs)
    return out


def paged_cache_write(pool, new, block_ids, offsets, out=None, name=None):
    """Scatter one new KV row per tick slot into the paged block pool —
    the block-granular counterpart of `cache_write` (serving/kv_pager.py).
    `pool` is [n_blocks, nh, block_size, dh]; `new` is [S, nh, dh];
    `block_ids`/`offsets` give each slot's physical target
    (pool[block_ids[s], :, offsets[s], :]). Pass the pool variable as
    `out` to round-trip the persistable pool through the executor's
    donated-state path, same as `cache_write(out=...)`."""
    helper = LayerHelper("paged_cache_write", name=name)
    if out is None:
        out = helper.create_tmp_variable(dtype=dtype_name(pool.dtype),
                                         shape=pool.shape,
                                         stop_gradient=True)
    helper.append_op(type="paged_cache_write",
                     inputs={"Cache": [pool], "New": [new],
                             "BlockIds": [block_ids],
                             "Offsets": [offsets]},
                     outputs={"Out": [out]})
    return out


def paged_cache_write_quant(pool, scales, new, block_ids, offsets,
                            out=None, scales_out=None, name=None):
    """int8 paged KV write: quantize each f32 row of `new` over its dh
    vector (symmetric amax/127) and scatter payload + per-row scale into
    `pool` (int8, [n_blocks, nh, block_size, dh]) and `scales` (f32,
    [n_blocks, nh, block_size, 1]). Returns (pool_out, scales_out); pass
    the pool variables themselves as `out`/`scales_out` to round-trip both
    through the executor's donated-state path, as `paged_cache_write`
    does. The read side dequantizes with one cast+multiply against the
    gathered scale view — XLA fuses it into the cache read, so the HBM
    resident AND streamed bytes are the int8 payload."""
    helper = LayerHelper("paged_cache_write_quant", name=name)
    if out is None:
        out = helper.create_tmp_variable(dtype=dtype_name(pool.dtype),
                                         shape=pool.shape,
                                         stop_gradient=True)
    if scales_out is None:
        scales_out = helper.create_tmp_variable(
            dtype=dtype_name(scales.dtype), shape=scales.shape,
            stop_gradient=True)
    helper.append_op(type="paged_cache_write_quant",
                     inputs={"Cache": [pool], "Scales": [scales],
                             "New": [new], "BlockIds": [block_ids],
                             "Offsets": [offsets]},
                     outputs={"Out": [out], "ScalesOut": [scales_out]})
    return out, scales_out


def lrn(input, n=5, k=2.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=input.shape)
    mid = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=input.shape, stop_gradient=True)
    helper.append_op(type="lrn", inputs={"X": [input]},
                     outputs={"Out": [out], "MidOut": [mid]},
                     attrs={"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype), shape=x.shape)
    norm = helper.create_tmp_variable(dtype=dtype_name(x.dtype),
                                      shape=x.shape, stop_gradient=True)
    helper.append_op(type="l2_normalize", inputs={"X": [x]},
                     outputs={"Out": [out], "Norm": [norm]},
                     attrs={"axis": axis, "epsilon": epsilon})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    helper = LayerHelper("label_smooth", name=name)
    inputs = {"X": [label]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist]
    out = helper.create_tmp_variable(dtype=dtype, shape=label.shape)
    helper.append_op(type="label_smooth", inputs=inputs,
                     outputs={"Out": [out]}, attrs={"epsilon": epsilon})
    return out


def slice(input, axes, starts, ends, name=None):
    """≙ reference slice_op.cc — static slice."""
    helper = LayerHelper("slice", name=name)
    out_shape = list(input.shape)
    for ax, s, e in zip(axes, starts, ends):
        if out_shape[ax] is not None and out_shape[ax] >= 0:
            dim = out_shape[ax]
            # python slice clamping semantics, matching the runtime x[s:e]
            s2 = min(max(s if s >= 0 else dim + s, 0), dim)
            e2 = min(max(e if e >= 0 else dim + e, 0), dim)
            out_shape[ax] = max(e2 - s2, 0)
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=out_shape)
    helper.append_op(type="slice", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends)})
    return out


# ---------------------------------------------------------------- losses
# ≙ reference nn.py / operators "Losses" family (SURVEY §2.2)


def rank_loss(label, left, right, name=None):
    """Pairwise RankNet loss (≙ rank_loss_op.cc)."""
    helper = LayerHelper("rank_loss", name=name)
    out = helper.create_tmp_variable(dtype=dtype_name(left.dtype),
                                     shape=left.shape)
    helper.append_op(type="rank_loss",
                     inputs={"Label": [label], "Left": [left],
                             "Right": [right]},
                     outputs={"Out": [out]})
    return out


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    """≙ margin_rank_loss_op.cc: max(0, -label*(left-right) + margin)."""
    helper = LayerHelper("margin_rank_loss", name=name)
    out = helper.create_tmp_variable(dtype=dtype_name(left.dtype),
                                     shape=left.shape)
    act = helper.create_tmp_variable(dtype=dtype_name(left.dtype),
                                     shape=left.shape, stop_gradient=True)
    helper.append_op(type="margin_rank_loss",
                     inputs={"Label": [label], "X1": [left], "X2": [right]},
                     outputs={"Out": [out], "Activated": [act]},
                     attrs={"margin": float(margin)})
    return out


def hinge_loss(input, label, name=None):
    """≙ hinge_loss_op.cc: max(0, 1 - input*(2*label-1))."""
    helper = LayerHelper("hinge_loss", name=name)
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=input.shape)
    helper.append_op(type="hinge_loss",
                     inputs={"Logits": [input], "Labels": [label]},
                     outputs={"Loss": [out]})
    return out


def log_loss(input, label, epsilon=1e-4, name=None):
    """≙ log_loss_op.cc: binary CE on probabilities."""
    helper = LayerHelper("log_loss", name=name)
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=input.shape)
    helper.append_op(type="log_loss",
                     inputs={"Predicted": [input], "Labels": [label]},
                     outputs={"Loss": [out]},
                     attrs={"epsilon": float(epsilon)})
    return out


def cos_sim(X, Y, name=None):
    """Row-wise cosine similarity; Y may be one row (≙ cos_sim_op.cc)."""
    helper = LayerHelper("cos_sim", name=name)
    out = helper.create_tmp_variable(dtype=dtype_name(X.dtype),
                                     shape=[X.shape[0], 1])
    xn = helper.create_tmp_variable(dtype=dtype_name(X.dtype),
                                    shape=[X.shape[0], 1],
                                    stop_gradient=True)
    yn = helper.create_tmp_variable(dtype=dtype_name(X.dtype),
                                    shape=[Y.shape[0], 1],
                                    stop_gradient=True)
    helper.append_op(type="cos_sim", inputs={"X": [X], "Y": [Y]},
                     outputs={"Out": [out], "XNorm": [xn], "YNorm": [yn]})
    return out


def squared_l2_norm(x, name=None):
    """sum(x**2) (≙ squared_l2_norm_op.cc)."""
    helper = LayerHelper("squared_l2_norm", name=name)
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype), shape=[1])
    helper.append_op(type="squared_l2_norm", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def squared_l2_distance(x, y, name=None):
    """Row-wise ||x-y||^2 (≙ squared_l2_distance_op.cc)."""
    helper = LayerHelper("squared_l2_distance", name=name)
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype),
                                     shape=[x.shape[0], 1])
    sub = helper.create_tmp_variable(dtype=dtype_name(x.dtype),
                                     shape=x.shape, stop_gradient=True)
    helper.append_op(type="squared_l2_distance",
                     inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out], "sub_result": [sub]})
    return out


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """out[n,k] = x[n] @ W_k @ y[n]^T (≙ bilinear_tensor_product_op.cc)."""
    helper = LayerHelper("bilinear_tensor_product", name=name, act=act,
                         param_attr=param_attr, bias_attr=bias_attr)
    dx, dy = x.shape[1], y.shape[1]
    w = helper.create_parameter(attr=param_attr, shape=[size, dx, dy],
                                dtype=dtype_name(x.dtype))
    inputs = {"X": [x], "Y": [y], "Weight": [w]}
    if bias_attr is not False:
        bias = helper.create_parameter(attr=bias_attr, shape=[1, size],
                                       dtype=dtype_name(x.dtype),
                                       is_bias=True)
        inputs["Bias"] = [bias]
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype),
                                     shape=[x.shape[0], size])
    helper.append_op(type="bilinear_tensor_product", inputs=inputs,
                     outputs={"Out": [out]})
    return helper.append_activation(out)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None):
    """NCE loss with a uniform negative sampler (≙ nce_op.cc + layers/nn.py
    nce). Returns per-example cost [N, 1]."""
    helper = LayerHelper("nce", name=name, param_attr=param_attr,
                         bias_attr=bias_attr)
    dim = input.shape[1]
    w = helper.create_parameter(attr=param_attr,
                                shape=[num_total_classes, dim],
                                dtype=dtype_name(input.dtype))
    inputs = {"Input": [input], "Label": [label], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(attr=bias_attr,
                                    shape=[num_total_classes],
                                    dtype=dtype_name(input.dtype),
                                    is_bias=True)
        inputs["Bias"] = [b]
    if sample_weight is not None:
        inputs["SampleWeight"] = [sample_weight]
    n = input.shape[0]
    cost = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                      shape=[n, 1])
    slog = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                      shape=[n, num_neg_samples + 1],
                                      stop_gradient=True)
    slab = helper.create_tmp_variable(dtype="int64",
                                      shape=[n, num_neg_samples + 1],
                                      stop_gradient=True)
    helper.append_op(type="nce", inputs=inputs,
                     outputs={"Cost": [cost], "SampleLogits": [slog],
                              "SampleLabels": [slab]},
                     attrs={"num_total_classes": int(num_total_classes),
                            "num_neg_samples": int(num_neg_samples)})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None):
    """Hierarchical sigmoid over a complete binary tree
    (≙ hsigmoid_op.cc + math/matrix_bit_code.h). Returns cost [N, 1]."""
    helper = LayerHelper("hierarchical_sigmoid", name=name,
                         param_attr=param_attr, bias_attr=bias_attr)
    dim = input.shape[1]
    from ..ops.loss_ops import hsigmoid_code_length
    max_len = hsigmoid_code_length(num_classes)
    w = helper.create_parameter(attr=param_attr,
                                shape=[num_classes - 1, dim],
                                dtype=dtype_name(input.dtype))
    inputs = {"X": [input], "Label": [label], "W": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(attr=bias_attr,
                                    shape=[num_classes - 1, 1],
                                    dtype=dtype_name(input.dtype),
                                    is_bias=True)
        inputs["Bias"] = [b]
    n = input.shape[0]
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=[n, 1])
    pre = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=[n, max_len], stop_gradient=True)
    helper.append_op(type="hierarchical_sigmoid", inputs=inputs,
                     outputs={"Out": [out], "PreOut": [pre]},
                     attrs={"num_classes": int(num_classes)})
    return out


def beam_search(pre_ids, pre_scores, scores, beam_size, end_id, name=None):
    """One beam-search growth step (≙ reference layers/nn.py beam_search:2706
    / beam_search_op.cc). Static-beam TPU translation: all tensors carry a
    fixed beam dim K = beam_size.

    pre_ids/pre_scores: [B, K]; scores: [B, K, V] per-step log-probs.
    Initialize pre_scores to 0 for beam 0 and a large negative (e.g. -1e9)
    for beams 1..K-1 so the first step expands a single hypothesis.
    Returns (selected_ids [B, K], selected_scores [B, K], parent_idx [B, K]).
    """
    helper = LayerHelper("beam_search", name=name)
    B = pre_ids.shape[0]
    sel_ids = helper.create_tmp_variable(dtype="int64", shape=[B, beam_size])
    sel_scores = helper.create_tmp_variable(dtype=dtype_name(scores.dtype),
                                            shape=[B, beam_size])
    parent = helper.create_tmp_variable(dtype="int64", shape=[B, beam_size])
    helper.append_op(type="beam_search",
                     inputs={"PreIds": [pre_ids], "PreScores": [pre_scores],
                             "Scores": [scores]},
                     outputs={"SelectedIds": [sel_ids],
                              "SelectedScores": [sel_scores],
                              "ParentIdx": [parent]},
                     attrs={"beam_size": int(beam_size),
                            "end_id": int(end_id)})
    return sel_ids, sel_scores, parent


def beam_search_decode(ids, parents, name=None):
    """Backtrack per-step beam selections into full sequences
    (≙ reference beam_search_decode / beam_search_decode_op.cc).
    ids/parents: [B, T, K] as collected by a decode loop emitting
    beam_search outputs. Returns sequences [B, T, K]."""
    helper = LayerHelper("beam_search_decode", name=name)
    out = helper.create_tmp_variable(dtype="int64", shape=list(ids.shape))
    helper.append_op(type="gather_tree",
                     inputs={"Ids": [ids], "Parents": [parents]},
                     outputs={"Out": [out]})
    return out


gather_tree = beam_search_decode


def log_softmax(x, axis=-1, name=None):
    """≙ log_softmax op (numerically stable log(softmax(x)))."""
    helper = LayerHelper("log_softmax", name=name)
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype), shape=x.shape)
    helper.append_op(type="log_softmax", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def fused_attention(q, k, v, scale=None, causal=False, segment_ids=None,
                    kv_segment_ids=None, name=None):
    """Fused scaled-dot-product attention over [B, H, T, D] tensors —
    flash kernel (Pallas) on TPU, XLA composite elsewhere
    (≙ nets.py scaled_dot_product_attention, kernelized).

    segment_ids ([B, T] int var) enables packed-batch masking — multiple
    sequences share one row and attend only within their own segment (the
    static-shape LoD translation, SURVEY §5); kv_segment_ids defaults to
    segment_ids (self-attention). Composes with `causal`."""
    helper = LayerHelper("fused_attention", name=name)
    out = helper.create_tmp_variable(dtype=dtype_name(q.dtype),
                                     shape=list(q.shape))
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if kv_segment_ids is not None and segment_ids is None:
        raise ValueError(
            "fused_attention: kv_segment_ids requires segment_ids (the "
            "query-side ids); pass both for cross-attention masking")
    if segment_ids is not None:
        inputs["QSeg"] = [segment_ids]
        inputs["KVSeg"] = [kv_segment_ids if kv_segment_ids is not None
                           else segment_ids]
    helper.append_op(type="fused_attention",
                     inputs=inputs,
                     outputs={"Out": [out]},
                     attrs={"scale": scale, "causal": causal})
    return out


def row_conv(input, future_context_size, param_attr=None, act=None,
             name=None):
    """≙ reference layers/nn.py row_conv (lookahead convolution).
    input [B, T, D]; future_context_size = lookahead window - 1."""
    helper = LayerHelper("row_conv", name=name, param_attr=param_attr,
                         act=act)
    d = input.shape[-1]
    w = helper.create_parameter(param_attr,
                                shape=[future_context_size + 1, d],
                                dtype=dtype_name(input.dtype))
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=list(input.shape))
    helper.append_op(type="row_conv",
                     inputs={"X": [input], "Filter": [w]},
                     outputs={"Out": [out]})
    return helper.append_activation(out)


def lstm_unit(x_t, cell_t_prev, forget_bias=0.0, name=None):
    """≙ reference layers lstm_unit: x_t [B, 4H] pre-projected gates.
    Returns (hidden, cell)."""
    helper = LayerHelper("lstm_unit", name=name)
    h = cell_t_prev.shape[-1]
    dtype = dtype_name(x_t.dtype)
    c = helper.create_tmp_variable(dtype=dtype, shape=list(cell_t_prev.shape))
    hid = helper.create_tmp_variable(dtype=dtype,
                                     shape=list(cell_t_prev.shape))
    helper.append_op(type="lstm_unit",
                     inputs={"X": [x_t], "C_prev": [cell_t_prev]},
                     outputs={"C": [c], "H": [hid]},
                     attrs={"forget_bias": float(forget_bias)})
    return hid, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             name=None):
    """≙ reference layers gru_unit: input [B, 3H] pre-projected; hidden
    [B, H]. Returns (new_hidden, reset_hidden_prev, gate)."""
    helper = LayerHelper("gru_unit", name=name, param_attr=param_attr)
    h = size // 3
    dtype = dtype_name(input.dtype)
    w = helper.create_parameter(param_attr, shape=[h, 3 * h], dtype=dtype)
    inputs = {"Input": [input], "HiddenPrev": [hidden], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, shape=[3 * h], dtype=dtype,
                                    is_bias=True)
        inputs["Bias"] = [b]
    new_h = helper.create_tmp_variable(dtype=dtype, shape=list(hidden.shape))
    gate = helper.create_tmp_variable(dtype=dtype,
                                      shape=[hidden.shape[0], 3 * h])
    reset = helper.create_tmp_variable(dtype=dtype,
                                       shape=list(hidden.shape))
    helper.append_op(type="gru_unit", inputs=inputs,
                     outputs={"Hidden": [new_h], "Gate": [gate],
                              "ResetHiddenPrev": [reset]})
    return new_h, reset, gate


def spp(input, pyramid_height=3, pool_type="max", name=None):
    """≙ reference layers spp (spatial pyramid pooling) — [N,C,H,W] ->
    [N, C * sum(4^l for l < pyramid_height)]."""
    helper = LayerHelper("spp", name=name)
    c = input.shape[1]
    total_bins = sum(4 ** l for l in range(pyramid_height))
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=[input.shape[0], c * total_bins])
    helper.append_op(type="spp", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"pyramid_height": pyramid_height,
                            "pooling_type": pool_type})
    return out
