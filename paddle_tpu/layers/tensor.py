"""Tensor-creation/manipulation layers.

≙ reference python/paddle/fluid/layers/tensor.py (create_tensor, cast, concat,
sums, assign, fill_constant, ones, zeros, reverse...).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..core.dtypes import convert_dtype, dtype_name
from ..layer_helper import LayerHelper


def create_tensor(dtype="float32", name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(name=helper.name, dtype=dtype,
                                  persistable=persistable)


def cast(x, dtype):
    helper = LayerHelper("cast")
    dtype = dtype_name(convert_dtype(dtype))
    out = helper.create_tmp_variable(dtype=dtype, shape=x.shape)
    helper.append_op(type="cast", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"out_dtype": dtype})
    return out


def concat(input: Sequence, axis: int = 0, name=None):
    helper = LayerHelper("concat", name=name)
    shapes = [v.shape for v in input]
    out_shape = list(shapes[0])
    if all(s is not None for s in shapes):
        ax = axis if axis >= 0 else len(out_shape) + axis
        if all(s[ax] != -1 for s in shapes):
            out_shape[ax] = sum(s[ax] for s in shapes)
        else:
            out_shape[ax] = -1
    out = helper.create_tmp_variable(dtype=dtype_name(input[0].dtype),
                                     shape=out_shape)
    helper.append_op(type="concat", inputs={"X": list(input)},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def sums(input: Sequence, out=None):
    helper = LayerHelper("sums")
    if out is None:
        out = helper.create_tmp_variable(dtype=dtype_name(input[0].dtype),
                                         shape=input[0].shape)
    helper.append_op(type="sum", inputs={"X": list(input)},
                     outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, np.ndarray):
        if output is None:
            output = helper.create_tmp_variable(
                dtype=dtype_name(input.dtype), shape=list(input.shape))
        helper.append_op(type="assign_value", outputs={"Out": [output]},
                         attrs={"shape": list(input.shape),
                                "dtype": dtype_name(input.dtype),
                                "values": input.reshape(-1).tolist()})
        return output
    if output is None:
        output = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                            shape=input.shape)
    helper.append_op(type="assign", inputs={"X": [input]},
                     outputs={"Out": [output]})
    return output


def fill_constant(shape, dtype, value, out=None, name=None):
    helper = LayerHelper("fill_constant", name=name)
    dtype = dtype_name(convert_dtype(dtype))
    if out is None:
        out = helper.create_tmp_variable(dtype=dtype, shape=list(shape),
                                         stop_gradient=True)
    helper.append_op(type="fill_constant", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "value": float(value)})
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    dtype = dtype_name(convert_dtype(dtype))
    out_shape = list(shape)
    out_shape[output_dim_idx] = -1
    out = helper.create_tmp_variable(dtype=dtype, shape=out_shape,
                                     stop_gradient=True)
    helper.append_op(type="fill_constant_batch_size_like",
                     inputs={"Input": [input]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "value": float(value),
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    return out


def ones(shape, dtype="float32"):
    return fill_constant(shape, dtype, 1.0)


def zeros(shape, dtype="float32"):
    return fill_constant(shape, dtype, 0.0)


def reverse(x, axis):
    helper = LayerHelper("reverse")
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype), shape=x.shape)
    axis = [axis] if isinstance(axis, int) else list(axis)
    helper.append_op(type="reverse", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like")
    if out is None:
        out = helper.create_tmp_variable(dtype=dtype_name(x.dtype),
                                         shape=x.shape, stop_gradient=True)
    helper.append_op(type="fill_zeros_like", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def argmin(x, axis=0):
    helper = LayerHelper("arg_min")
    shape = list(x.shape)
    shape.pop(axis if axis >= 0 else len(shape) + axis)
    out = helper.create_tmp_variable(dtype="int64", shape=shape,
                                     stop_gradient=True)
    helper.append_op(type="arg_min", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def argmax(x, axis=0):
    helper = LayerHelper("arg_max")
    shape = list(x.shape)
    shape.pop(axis if axis >= 0 else len(shape) + axis)
    out = helper.create_tmp_variable(dtype="int64", shape=shape,
                                     stop_gradient=True)
    helper.append_op(type="arg_max", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def argsort(x, axis=-1):
    helper = LayerHelper("argsort")
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype), shape=x.shape,
                                     stop_gradient=True)
    ids = helper.create_tmp_variable(dtype="int64", shape=x.shape,
                                     stop_gradient=True)
    helper.append_op(type="argsort", inputs={"X": [x]},
                     outputs={"Out": [out], "Indices": [ids]},
                     attrs={"axis": axis})
    return out, ids
