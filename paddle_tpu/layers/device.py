"""Device layer helpers (≙ reference python/paddle/fluid/layers/device.py)."""

from __future__ import annotations

from ..core import places as _places
from ..core.places import Place


def get_places(device_count=None, device_type=None):
    """≙ reference layers.device.get_places (used by ParallelDo-era code):
    list the visible device Places. Multi-device execution goes through
    ParallelExecutor/pjit; this exists for API parity and introspection.

    device_type: None (all), "CPU", or "TPU"/"GPU" (any accelerator)."""
    devs = _places.devices()
    if device_type == "CPU":
        devs = [d for d in devs if d.platform == "cpu"]
    elif device_type in ("GPU", "TPU"):
        devs = [d for d in devs if d.platform != "cpu"]
    if device_count:
        devs = devs[:device_count]
    # device_id is the KIND-LOCAL index (what place_to_device expects),
    # paired with the device's real kind so the place resolves back
    counters: dict = {}
    out = []
    for d in devs:
        k = _places.kind_of(d.platform)
        i = counters.get(k, 0)
        counters[k] = i + 1
        out.append(Place(k, i))
    return out
