"""Device layer helpers (≙ reference python/paddle/fluid/layers/device.py)."""

from __future__ import annotations

from ..core import places as _places
from ..core.places import CPUPlace, TPUPlace


def get_places(device_count=None, device_type=None):
    """≙ reference layers.device.get_places (used by ParallelDo-era code):
    list the visible device Places. Multi-device execution goes through
    ParallelExecutor/pjit; this exists for API parity and introspection.

    device_type: None (all), "CPU", or "TPU"/"GPU" (accelerators)."""
    kind = None
    if device_type == "CPU":
        kind = "cpu"
    elif device_type in ("GPU", "TPU"):
        kind = "tpu"   # "GPU" means "the accelerators" in reference code
    devs = _places.devices(kind)   # handles platform aliases (axon -> tpu)
    if device_count:
        devs = devs[:device_count]
    # device_id is the KIND-LOCAL index (what place_to_device expects),
    # not jax's global id; accelerator = anything that is not host cpu
    cpu_i = 0
    acc_i = 0
    out = []
    for d in devs:
        if d.platform == "cpu":
            out.append(CPUPlace(cpu_i))
            cpu_i += 1
        else:
            out.append(TPUPlace(acc_i))
            acc_i += 1
    return out
