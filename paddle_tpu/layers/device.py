"""Device layer helpers (≙ reference python/paddle/fluid/layers/device.py)."""

from __future__ import annotations

from ..core import places as _places
from ..core.places import CPUPlace, TPUPlace


def get_places(device_count=None, device_type=None):
    """≙ reference layers.device.get_places (used by ParallelDo-era code):
    list the visible device Places. Multi-device execution goes through
    ParallelExecutor/pjit; this exists for API parity and introspection.

    device_type: None (all), "CPU", or "TPU"/"GPU" (accelerators)."""
    kind = None
    if device_type == "CPU":
        kind = "cpu"
    elif device_type in ("GPU", "TPU"):
        kind = "tpu"
    devs = _places.devices(kind)   # handles platform aliases (axon -> tpu)
    if device_count:
        devs = devs[:device_count]
    tpu_aliases = _places._KIND_ALIASES.get("tpu", ("tpu",))
    return [TPUPlace(d.id) if d.platform in tpu_aliases else CPUPlace(d.id)
            for d in devs]
