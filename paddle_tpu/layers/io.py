"""IO layers: data declaration.

≙ reference python/paddle/fluid/layers/io.py (`data`:38). The reader-op stack
(py_reader/open_files/double_buffer, io.py:345-921) is replaced by the host
data pipeline in paddle_tpu.data (reader decorators + prefetching feeder) —
on TPU, input feeding is host-side with async device puts, not in-graph
reader ops.
"""

from __future__ import annotations

from ..framework.program import default_main_program, default_startup_program


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True):
    """Declare an input variable (≙ fluid.layers.data, reference
    layers/io.py:38). append_batch_size prepends -1."""
    full_shape = list(shape)
    if append_batch_size:
        full_shape = [-1] + full_shape
    block = default_main_program().current_block()
    if name in block.vars:
        return block.vars[name]
    var = block.create_var(name=name, shape=full_shape, dtype=dtype,
                           lod_level=lod_level, is_data=True,
                           stop_gradient=stop_gradient)
    if lod_level > 0:
        # companion sequence-length variable (static-shape LoD translation)
        block.create_var(name=name + "@SEQLEN", shape=[-1], dtype="int32",
                         is_data=True, stop_gradient=True)
    return var
