"""IO layers: data declaration.

≙ reference python/paddle/fluid/layers/io.py (`data`:38). The reader-op stack
(py_reader/open_files/double_buffer, io.py:345-921) is replaced by the host
data pipeline in paddle_tpu.data (reader decorators + prefetching feeder) —
on TPU, input feeding is host-side with async device puts, not in-graph
reader ops.
"""

from __future__ import annotations

import numpy as np

from ..core.dtypes import convert_dtype
from ..framework.program import BATCH_ROW_MASK_NAME, default_main_program


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True, staging_dtype=None, staging_scale=None):
    """Declare an input variable (≙ fluid.layers.data, reference
    layers/io.py:38). append_batch_size prepends -1.

    staging_dtype declares a byte-lean wire dtype: the host may feed this
    var as `staging_dtype` (e.g. uint8 images — 4x fewer bytes over the
    host->device link than fp32) and the compiled step casts to `dtype` and
    multiplies by `staging_scale` (default 1/255 for uint8->float) on
    device. Feeding the declared `dtype` directly remains valid — the cast
    is keyed off the fed dtype at compile time.
    """
    full_shape = list(shape)
    if append_batch_size:
        full_shape = [-1] + full_shape
    block = default_main_program().current_block()
    if name in block.vars:
        return block.vars[name]
    var = block.create_var(name=name, shape=full_shape, dtype=dtype,
                           lod_level=lod_level, is_data=True,
                           stop_gradient=stop_gradient)
    if staging_dtype is not None:
        # canonicalize (accepts "uint8", np.uint8, np.dtype("uint8"), ...)
        # so the uint8 default-scale rule and downstream dtype comparisons
        # can't be defeated by the spelling of the dtype
        wire = convert_dtype(staging_dtype)
        if staging_scale is None and wire == np.dtype(np.uint8):
            staging_scale = 1.0 / 255.0
        var.staging = (wire, staging_scale)
    if lod_level > 0:
        # companion sequence-length variable (static-shape LoD translation)
        block.create_var(name=name + "@SEQLEN", shape=[-1], dtype="int32",
                         is_data=True, stop_gradient=True)
    return var


def batch_row_mask():
    """Declare the per-row batch validity mask: [batch] float32, 1.0 for
    real rows, 0.0 for rows a ParallelExecutor padded to make a partial
    last batch dp-divisible (≙ reference details/data_balance_op_handle.cc,
    which redistributes uneven reader batches so every device can run).

    Feeding is automatic: Executor feeds all-ones when the caller doesn't;
    ParallelExecutor zeroes padded rows. Weight per-example losses with it —
    ``loss = reduce_sum(per_ex * mask) / reduce_sum(mask)`` — so padded rows
    contribute exactly nothing to the gradient."""
    block = default_main_program().current_block()
    if BATCH_ROW_MASK_NAME in block.vars:
        return block.vars[BATCH_ROW_MASK_NAME]
    return block.create_var(name=BATCH_ROW_MASK_NAME, shape=[-1],
                            dtype="float32", is_data=True,
                            stop_gradient=True)


# ---------------------------------------------------------------------------
# Reader pipeline (≙ reference layers/io.py:345-968: open_recordio_file,
# py_reader, open_files, shuffle/batch/double_buffer decorators,
# Preprocessor). TPU translation: readers are python iterators over feed
# dicts; py_reader is a bounded blocking queue decoupling a producer thread
# from the train loop (≙ LoDTensorBlockingQueue, reader/
# lod_tensor_blocking_queue.h:31); double-buffering stages batches onto the
# device ahead of compute (≙ buffered_reader.h:27).
# ---------------------------------------------------------------------------

class PyReader:
    """Queue-fed async input (≙ layers/io.py py_reader:474).

    feed_list names the data vars each record provides. A producer thread
    calls decorate_* then start(); the train loop iterates feed dicts.
    """

    def __init__(self, feed_list, capacity=64, name=None,
                 use_double_buffer=False):
        import queue as _q
        self.feed_names = [getattr(v, "name", v) for v in feed_list]
        self._capacity = capacity
        self._queue = _q.Queue(maxsize=capacity)
        self._END = object()
        self._thread = None
        self._gen = None
        self._err = []
        self.use_double_buffer = use_double_buffer

    def decorate_sample_list_generator(self, generator):
        """generator() yields lists/tuples aligned with feed_list."""
        self._gen = generator
        return self

    decorate_paddle_reader = decorate_sample_list_generator  # API parity

    def start(self):
        import queue as _q
        import threading

        # bind everything per-epoch: a later reset() must neither receive
        # this producer's data nor its errors, and must be able to stop it
        q = self._queue
        err = self._err
        stop = threading.Event()
        self._stop = stop

        def produce():
            try:
                for sample in self._gen():
                    if not isinstance(sample, dict):
                        sample = dict(zip(self.feed_names, sample))
                    while not stop.is_set():
                        try:
                            q.put(sample, timeout=0.1)
                            break
                        except _q.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:  # surfaced in the consumer
                err.append(e)
            finally:
                # END must actually arrive or the consumer hangs; only a
                # reset() (stop set) may abandon delivery — that queue is
                # orphaned and nobody reads it
                while not stop.is_set():
                    try:
                        q.put(self._END, timeout=0.1)
                        break
                    except _q.Full:
                        continue
        self._thread = threading.Thread(target=produce, daemon=True)
        self._thread.start()
        return self

    def reset(self):
        """Abandon the current epoch: signal the producer to exit (it stops
        at its next put attempt) and swap in a fresh queue/error list so no
        stale samples or errors leak into the next start()."""
        import queue as _q
        if getattr(self, "_stop", None) is not None:
            self._stop.set()
        self._queue = _q.Queue(maxsize=self._capacity)
        self._thread = None
        self._err = []

    def _raw_iter(self):
        q = self._queue
        while True:
            item = q.get()
            if item is self._END:
                if self._err:
                    raise self._err[0]
                return
            yield item

    def __iter__(self):
        if self.use_double_buffer:
            from ..data.prefetch import DevicePrefetcher
            yield from DevicePrefetcher(self._raw_iter)
        else:
            yield from self._raw_iter()


def py_reader(capacity, shapes, dtypes, names, use_double_buffer=True):
    """≙ reference layers/io.py py_reader:474 — declares the data vars and
    returns a PyReader bound to them. `use_double_buffer` composes the
    device prefetcher (see double_buffer)."""
    feed_vars = []
    for nm, shape, dtype in zip(names, shapes, dtypes):
        feed_vars.append(data(nm, shape=list(shape), dtype=dtype,
                              append_batch_size=False))
    return PyReader(feed_vars, capacity=capacity,
                    use_double_buffer=use_double_buffer)


def open_recordio_file(filename, shapes, dtypes, names):
    """≙ layers/io.py open_recordio_file:345 — a reader over the native
    chunked record container (paddle_tpu/native/recordio.cc). Records are
    flat float32/int payloads written by data.recordio.RecordIOWriter;
    each record deserializes per `shapes`/`dtypes` into a feed dict."""
    import numpy as np

    from ..data.recordio import RecordIOScanner

    def reader():
        with RecordIOScanner(filename) as sc:
            for rec in sc:
                out = {}
                off = 0
                for nm, shape, dtype in zip(names, shapes, dtypes):
                    arr = np.frombuffer(rec, dtype=dtype, offset=off,
                                        count=int(np.prod(shape)))
                    out[nm] = arr.reshape(shape).copy()
                    off += arr.nbytes
                if off != len(rec):
                    raise ValueError(
                        f"record in {filename!r} has {len(rec)} bytes but "
                        f"shapes/dtypes consume {off} — shape or dtype "
                        f"misconfiguration (no silent data loss)")
                yield out
    return reader


def open_files(filenames, shapes, dtypes, names, thread_num=1):
    """≙ layers/io.py open_files:724 — multi-file recordio reader; files
    are interleaved (thread_num kept for API parity; IO parallelism comes
    from the native loader + prefetcher)."""
    def reader():
        for fn in filenames:
            yield from open_recordio_file(fn, shapes, dtypes, names)()
    return reader


def shuffle(reader, buffer_size):
    """≙ layers/io.py shuffle:843 (reader-level)."""
    from ..data import decorator
    return decorator.shuffle(reader, buffer_size)


def batch(reader, batch_size, drop_last=True):
    """≙ layers/io.py batch (reader-level): stacks per-key feed dicts."""
    import numpy as np

    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield {k: np.stack([b[k] for b in buf]) for k in buf[0]}
                buf = []
        if buf and not drop_last:
            yield {k: np.stack([b[k] for b in buf]) for k in buf[0]}
    return batched


def double_buffer(reader, place=None):
    """≙ layers/io.py double_buffer:921 — stage upcoming batches on device
    while the current step computes (DevicePrefetcher). Keeps the reader
    contract: returns a zero-arg callable, composable with batch/shuffle.
    `place` accepted for API parity (XLA owns placement)."""
    from ..data.prefetch import DevicePrefetcher

    def buffered():
        yield from DevicePrefetcher(reader)
    return buffered


class Preprocessor:
    """≙ layers/io.py Preprocessor:968 — user-defined transform stage in
    the reader pipeline.

        p = Preprocessor(reader)
        @p.def_transform
        def _(sample): ...
        new_reader = p()
    """

    def __init__(self, reader, name=None):
        self._reader = reader
        self._fn = None

    def def_transform(self, fn):
        self._fn = fn
        return fn

    def __call__(self):
        def transformed():
            for item in self._reader():
                out = self._fn(item)
                if out is not None:
                    yield out
        return transformed
