"""Sequence-family layer wrappers (≙ reference layers/nn.py sequence_* +
dynamic_lstm:290 / dynamic_gru).

A "sequence" variable here is a dense padded [B, T, ...] array plus a
companion int32 length vector (the static-shape translation of the
reference's LoD, see paddle_tpu/ops/sequence_ops.py). Layers locate the
companion via ``var.seqlen_var`` (propagated through sequence layers) or the
``<name>@SEQLEN`` block variable created by ``layers.data(lod_level>0)``.
"""

from __future__ import annotations

from ..core.dtypes import dtype_name
from ..core.enforce import InvalidArgumentError, NotFoundError, enforce
from ..layer_helper import LayerHelper


def get_seqlen(var):
    """Resolve the companion sequence-length variable of a padded sequence."""
    sl = getattr(var, "seqlen_var", None)
    if sl is not None:
        return sl
    name = var.name + "@SEQLEN"
    v = var.block.find_var_recursive(name) if hasattr(
        var.block, "find_var_recursive") else var.block.vars.get(name)
    enforce(v is not None,
            f"variable {var.name!r} has no sequence-length companion; "
            f"declare it with layers.data(..., lod_level=1) or propagate "
            f"seqlen_var", exc=NotFoundError)
    return v


def tag_sequence(out, seqlen):
    """Mark `out` as a sequence sharing `seqlen`. Returns out."""
    out.seqlen_var = seqlen
    return out


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """≙ reference layers/nn.py:290 (dynamic_lstm). `input` is the
    pre-projected [B, T, 4H] sequence (apply fc first, as the reference
    requires); size = 4 * hidden. Returns (hidden, cell), both [B, T, H]
    sequences."""
    enforce(size % 4 == 0, "dynamic_lstm size must be 4*hidden",
            exc=InvalidArgumentError)
    helper = LayerHelper("dynamic_lstm", name=name)
    hidden_size = size // 4
    seqlen = get_seqlen(input)
    weight = helper.create_parameter(param_attr,
                                     shape=[hidden_size, 4 * hidden_size],
                                     dtype=dtype)
    bias = helper.create_parameter(
        bias_attr, shape=[7 * hidden_size if use_peepholes
                          else 4 * hidden_size],
        dtype=dtype, is_bias=True)
    b, t = input.shape[0], input.shape[1]
    hidden = helper.create_tmp_variable(dtype=dtype,
                                        shape=[b, t, hidden_size])
    cell = helper.create_tmp_variable(dtype=dtype, shape=[b, t, hidden_size])
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias],
              "SeqLen": [seqlen]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    helper.append_op(type="dynamic_lstm", inputs=inputs,
                     outputs={"Hidden": [hidden], "Cell": [cell]},
                     attrs={"use_peepholes": use_peepholes,
                            "is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "cell_activation": cell_activation,
                            "candidate_activation": candidate_activation})
    return tag_sequence(hidden, seqlen), tag_sequence(cell, seqlen)


def dynamic_lstmp(input, size, proj_size, h_0=None, c_0=None,
                  param_attr=None, bias_attr=None, use_peepholes=True,
                  is_reverse=False, gate_activation="sigmoid",
                  cell_activation="tanh", candidate_activation="tanh",
                  proj_activation="identity", dtype="float32", name=None):
    """≙ reference layers/nn.py dynamic_lstmp (lstmp_op.cc): LSTM with a
    recurrent projection layer. `input` is the pre-projected [B, T, 4H]
    sequence; size = 4 * hidden; proj_size = P. Returns (projection, cell):
    [B, T, P] and [B, T, H]."""
    enforce(size % 4 == 0, "dynamic_lstmp size must be 4*hidden",
            exc=InvalidArgumentError)
    helper = LayerHelper("dynamic_lstmp", name=name)
    hidden_size = size // 4
    seqlen = get_seqlen(input)
    weight = helper.create_parameter(param_attr,
                                     shape=[proj_size, 4 * hidden_size],
                                     dtype=dtype)
    # the projection weight must NOT alias the recurrent weight when the
    # caller names param_attr (create_parameter returns the existing var for
    # a repeated name) — derive a distinct name, keeping every other attr
    # (trainable/regularizer/lr/clip/sharding)
    import copy as _copy
    from ..param_attr import ParamAttr
    proj_attr = param_attr
    if isinstance(param_attr, ParamAttr) and param_attr.name:
        proj_attr = _copy.copy(param_attr)
        proj_attr.name = param_attr.name + "_proj"
    proj_weight = helper.create_parameter(proj_attr,
                                          shape=[hidden_size, proj_size],
                                          dtype=dtype)
    bias = helper.create_parameter(
        bias_attr, shape=[7 * hidden_size if use_peepholes
                          else 4 * hidden_size],
        dtype=dtype, is_bias=True)
    b, t = input.shape[0], input.shape[1]
    proj = helper.create_tmp_variable(dtype=dtype, shape=[b, t, proj_size])
    cell = helper.create_tmp_variable(dtype=dtype,
                                      shape=[b, t, hidden_size])
    inputs = {"Input": [input], "Weight": [weight],
              "ProjWeight": [proj_weight], "Bias": [bias],
              "SeqLen": [seqlen]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    helper.append_op(type="dynamic_lstmp",
                     inputs=inputs,
                     outputs={"Projection": [proj], "Cell": [cell]},
                     attrs={"use_peepholes": use_peepholes,
                            "is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "cell_activation": cell_activation,
                            "candidate_activation": candidate_activation,
                            "proj_activation": proj_activation})
    return tag_sequence(proj, seqlen), tag_sequence(cell, seqlen)


def sequence_reshape(input, new_dim):
    """≙ reference layers/nn.py sequence_reshape (sequence_reshape_op.cc):
    change the feature width, scaling every sequence length by
    old_dim / new_dim."""
    helper = LayerHelper("sequence_reshape", name=None)
    seqlen = get_seqlen(input)
    b, t, d = input.shape
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=[b, (t * d) // new_dim, new_dim])
    new_len = helper.create_tmp_variable(dtype="int32", shape=[b])
    helper.append_op(type="sequence_reshape",
                     inputs={"X": [input], "SeqLen": [seqlen]},
                     outputs={"Out": [out], "SeqLenOut": [new_len]},
                     attrs={"new_dim": new_dim})
    return tag_sequence(out, new_len)


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, name=None):
    """≙ reference layers/nn.py dynamic_gru. `input` is pre-projected
    [B, T, 3H]; size = hidden. Returns hidden sequence [B, T, H]."""
    helper = LayerHelper("dynamic_gru", name=name)
    seqlen = get_seqlen(input)
    dtype = dtype_name(input.dtype)
    weight = helper.create_parameter(param_attr, shape=[size, 3 * size],
                                     dtype=dtype)
    bias = helper.create_parameter(bias_attr, shape=[3 * size], dtype=dtype,
                                   is_bias=True)
    b, t = input.shape[0], input.shape[1]
    hidden = helper.create_tmp_variable(dtype=dtype, shape=[b, t, size])
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias],
              "SeqLen": [seqlen]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    helper.append_op(type="dynamic_gru", inputs=inputs,
                     outputs={"Hidden": [hidden]},
                     attrs={"is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "activation": candidate_activation})
    return tag_sequence(hidden, seqlen)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None,
                  name=None):
    """≙ reference layers/nn.py sequence_conv (context-window conv)."""
    helper = LayerHelper("sequence_conv", name=name, act=act,
                         bias_attr=bias_attr)
    seqlen = get_seqlen(input)
    dtype = dtype_name(input.dtype)
    d = input.shape[-1]
    filter_shape = [filter_size * d, num_filters]
    filter_param = helper.create_parameter(param_attr, shape=filter_shape,
                                           dtype=dtype)
    b, t = input.shape[0], input.shape[1]
    out = helper.create_tmp_variable(dtype=dtype, shape=[b, t, num_filters])
    helper.append_op(type="sequence_conv",
                     inputs={"X": [input], "Filter": [filter_param],
                             "SeqLen": [seqlen]},
                     outputs={"Out": [out]},
                     attrs={"contextLength": filter_size,
                            "contextStart": -(filter_size // 2),
                            "contextStride": filter_stride})
    out = helper.append_bias_op(out)
    return tag_sequence(helper.append_activation(out), seqlen)


def sequence_pool(input, pool_type="average", name=None):
    """≙ reference layers/nn.py sequence_pool. Pools [B, T, D] -> [B, D]
    over valid timesteps."""
    helper = LayerHelper("sequence_pool", name=name)
    seqlen = get_seqlen(input)
    dtype = dtype_name(input.dtype)
    out_shape = [input.shape[0]] + list(input.shape[2:])
    out = helper.create_tmp_variable(dtype=dtype, shape=out_shape)
    helper.append_op(type="sequence_pool",
                     inputs={"X": [input], "SeqLen": [seqlen]},
                     outputs={"Out": [out]},
                     attrs={"pooltype": pool_type.upper()})
    return out


def sequence_softmax(input, name=None):
    helper = LayerHelper("sequence_softmax", name=name)
    seqlen = get_seqlen(input)
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=input.shape)
    helper.append_op(type="sequence_softmax",
                     inputs={"X": [input], "SeqLen": [seqlen]},
                     outputs={"Out": [out]})
    return tag_sequence(out, seqlen)


def sequence_first_step(input, name=None):
    helper = LayerHelper("sequence_first_step", name=name)
    seqlen = get_seqlen(input)
    out_shape = [input.shape[0]] + list(input.shape[2:])
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=out_shape)
    helper.append_op(type="sequence_first_step",
                     inputs={"X": [input], "SeqLen": [seqlen]},
                     outputs={"Out": [out]})
    return out


def sequence_last_step(input, name=None):
    helper = LayerHelper("sequence_last_step", name=name)
    seqlen = get_seqlen(input)
    out_shape = [input.shape[0]] + list(input.shape[2:])
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=out_shape)
    helper.append_op(type="sequence_last_step",
                     inputs={"X": [input], "SeqLen": [seqlen]},
                     outputs={"Out": [out]})
    return out


def sequence_reverse(x, name=None):
    helper = LayerHelper("sequence_reverse", name=name)
    seqlen = get_seqlen(x)
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype), shape=x.shape)
    helper.append_op(type="sequence_reverse",
                     inputs={"X": [x], "SeqLen": [seqlen]},
                     outputs={"Y": [out]})
    return tag_sequence(out, seqlen)


def sequence_expand(x, y, name=None):
    """Broadcast per-sequence vector x [B, D] over y's time dim."""
    helper = LayerHelper("sequence_expand", name=name)
    seqlen = get_seqlen(y)
    out = helper.create_tmp_variable(
        dtype=dtype_name(x.dtype),
        shape=[x.shape[0], y.shape[1]] + list(x.shape[1:]))
    helper.append_op(type="sequence_expand",
                     inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]})
    return tag_sequence(out, seqlen)


def sequence_concat(input, name=None):
    """Concatenate sequences along the feature dim."""
    helper = LayerHelper("sequence_concat", name=name)
    xs = input if isinstance(input, (list, tuple)) else [input]
    seqlen = get_seqlen(xs[0])
    feat = sum(x.shape[-1] for x in xs)
    out = helper.create_tmp_variable(dtype=dtype_name(xs[0].dtype),
                                     shape=list(xs[0].shape[:-1]) + [feat])
    helper.append_op(type="sequence_concat", inputs={"X": list(xs)},
                     outputs={"Out": [out]})
    return tag_sequence(out, seqlen)


def sequence_slice(input, offset, length, name=None):
    helper = LayerHelper("sequence_slice", name=name)
    seqlen = get_seqlen(input)
    out = helper.create_tmp_variable(
        dtype=dtype_name(input.dtype),
        shape=[input.shape[0], int(length)] + list(input.shape[2:]))
    helper.append_op(type="sequence_slice",
                     inputs={"X": [input], "Offset": [offset]},
                     outputs={"Out": [out]}, attrs={"length": int(length)})
    return tag_sequence(out, seqlen)


def sequence_pad(x, pad_value=None, maxlen=None, name=None):
    """Already-padded representation: identity + lengths (API parity with
    reference sequence_pad)."""
    helper = LayerHelper("sequence_pad", name=name)
    seqlen = get_seqlen(x)
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype), shape=x.shape)
    length = helper.create_tmp_variable(dtype="int32",
                                        shape=[x.shape[0]])
    helper.append_op(type="sequence_pad",
                     inputs={"X": [x], "SeqLen": [seqlen]},
                     outputs={"Out": [out], "Length": [length]})
    return out, length


def sequence_erase(input, tokens, name=None):
    helper = LayerHelper("sequence_erase", name=name)
    seqlen = get_seqlen(input)
    out = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                     shape=input.shape)
    mask = helper.create_tmp_variable(dtype="int32", shape=input.shape)
    helper.append_op(type="sequence_erase",
                     inputs={"X": [input]},
                     outputs={"Out": [out], "Mask": [mask]},
                     attrs={"tokens": list(tokens)})
    return tag_sequence(out, seqlen)


def sequence_mask(x, maxlen, dtype="float32", name=None):
    """[B] lengths -> [B, maxlen] 0/1 mask (≙ reference sequence_mask).
    maxlen must be static on TPU."""
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_tmp_variable(dtype=dtype,
                                     shape=[x.shape[0], int(maxlen)])
    helper.append_op(type="sequence_mask", inputs={"X": [x]},
                     outputs={"Y": [out]}, attrs={"maxlen": int(maxlen)})
    return out


# ---------------------------------------------------------------------------
# Sequence labeling: CTC, CRF, chunk evaluation
# (≙ reference layers/nn.py warpctc, linear_chain_crf, crf_decoding and
#  layers ctc_greedy_decoder / chunk_eval)
# ---------------------------------------------------------------------------

def warpctc(input, label, input_length, label_length, blank=0,
            norm_by_times=False, name=None):
    """CTC loss (≙ reference layers/nn.py warpctc / operators/warpctc_op.cc).

    input: [B, T, C] unnormalized logits; label: [B, L] int;
    input_length/label_length: [B]. Returns Loss [B, 1].
    """
    helper = LayerHelper("warpctc", name=name)
    loss = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                      shape=[input.shape[0], 1])
    helper.append_op(type="warpctc",
                     inputs={"Logits": [input], "Label": [label],
                             "LogitsLength": [input_length],
                             "LabelLength": [label_length]},
                     outputs={"Loss": [loss]},
                     attrs={"blank": int(blank),
                            "norm_by_times": bool(norm_by_times)})
    return loss


def ctc_greedy_decoder(input, blank, input_length, name=None):
    """Greedy (best-path) CTC decode: per-step argmax then merge-repeats +
    drop-blanks (≙ reference ctc_greedy_decoder = top_k + ctc_align).

    input: [B, T, C] probabilities/logits. Returns (decoded [B, T],
    decoded_length [B, 1])."""
    helper = LayerHelper("ctc_greedy_decoder", name=name)
    best = helper.create_tmp_variable(dtype="int64",
                                      shape=list(input.shape[:2]))
    helper.append_op(type="arg_max", inputs={"X": [input]},
                     outputs={"Out": [best]}, attrs={"axis": -1})
    out = helper.create_tmp_variable(dtype="int64",
                                     shape=list(input.shape[:2]))
    out_len = helper.create_tmp_variable(dtype="int64",
                                         shape=[input.shape[0], 1])
    helper.append_op(type="ctc_align",
                     inputs={"Input": [best],
                             "InputLength": [input_length]},
                     outputs={"Output": [out], "OutputLength": [out_len]},
                     attrs={"blank": int(blank), "padding_value": 0})
    return out, out_len


def linear_chain_crf(input, label, length, param_attr=None, name=None):
    """Linear-chain CRF negative log-likelihood
    (≙ reference layers/nn.py linear_chain_crf / linear_chain_crf_op.cc).

    input: [B, T, D] emissions; label: [B, T] int; length: [B].
    Creates the [D+2, D] transition parameter (row 0 start, row 1 end,
    rows 2.. transitions) and returns Loss [B, 1]."""
    helper = LayerHelper("linear_chain_crf", name=name,
                         param_attr=param_attr)
    ntags = input.shape[-1]
    transition = helper.create_parameter(attr=param_attr,
                                         shape=[ntags + 2, ntags],
                                         dtype=dtype_name(input.dtype))
    ll = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                    shape=[input.shape[0], 1])
    alpha = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                       shape=[input.shape[0], ntags])
    e_exp = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                       shape=list(input.shape))
    t_exp = helper.create_tmp_variable(dtype=dtype_name(input.dtype),
                                       shape=[ntags + 2, ntags])
    helper.append_op(type="linear_chain_crf",
                     inputs={"Emission": [input], "Transition": [transition],
                             "Label": [label], "Length": [length]},
                     outputs={"LogLikelihood": [ll], "Alpha": [alpha],
                              "EmissionExps": [e_exp],
                              "TransitionExps": [t_exp]})
    return ll


def crf_decoding(input, length, param_attr=None, label=None, name=None):
    """Viterbi decode against a trained CRF transition parameter
    (≙ reference layers/nn.py crf_decoding / crf_decoding_op.cc). The
    transition param is resolved by name from param_attr (share it with the
    linear_chain_crf layer). With `label`, returns the 1/0 correctness mask
    the reference emits instead of the path."""
    helper = LayerHelper("crf_decoding", name=name, param_attr=param_attr)
    ntags = input.shape[-1]
    transition = helper.create_parameter(attr=param_attr,
                                         shape=[ntags + 2, ntags],
                                         dtype=dtype_name(input.dtype))
    path = helper.create_tmp_variable(dtype="int64",
                                      shape=list(input.shape[:2]))
    inputs = {"Emission": [input], "Transition": [transition],
              "Length": [length]}
    if label is not None:
        inputs["Label"] = [label]
    helper.append_op(type="crf_decoding", inputs=inputs,
                     outputs={"ViterbiPath": [path]})
    return path


def chunk_eval(input, label, length, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, name=None):
    """Chunk-level precision/recall/F1 (≙ reference layers chunk_eval /
    chunk_eval_op.cc). Returns (precision, recall, f1, num_infer_chunks,
    num_label_chunks, num_correct_chunks)."""
    helper = LayerHelper("chunk_eval", name=name)
    mk = helper.create_tmp_variable
    precision = mk(dtype="float32", shape=[1])
    recall = mk(dtype="float32", shape=[1])
    f1 = mk(dtype="float32", shape=[1])
    n_inf = mk(dtype="int64", shape=[1])
    n_lab = mk(dtype="int64", shape=[1])
    n_cor = mk(dtype="int64", shape=[1])
    helper.append_op(type="chunk_eval",
                     inputs={"Inference": [input], "Label": [label],
                             "Length": [length]},
                     outputs={"Precision": [precision], "Recall": [recall],
                              "F1-Score": [f1], "NumInferChunks": [n_inf],
                              "NumLabelChunks": [n_lab],
                              "NumCorrectChunks": [n_cor]},
                     attrs={"chunk_scheme": chunk_scheme,
                            "num_chunk_types": int(num_chunk_types),
                            "excluded_chunk_types":
                                list(excluded_chunk_types or [])})
    return precision, recall, f1, n_inf, n_lab, n_cor


def _as_lengths_var(v, what):
    """Accept a tagged sequence (its lengths are extracted) or a rank-1
    integer lengths Variable; anything else is rejected loudly."""
    from ..framework.program import Variable
    enforce(isinstance(v, Variable),
            f"{what} must be a Variable (a tagged sequence or a [B] int "
            f"lengths vector); got {type(v).__name__} — note: this "
            f"framework's 'LoD' is per-sequence LENGTHS, not offset lists",
            exc=InvalidArgumentError)
    try:
        return get_seqlen(v)
    except NotFoundError:
        is_len_vec = (len(v.shape or ()) == 1 and
                      "int" in str(v.dtype))
        enforce(is_len_vec,
                f"{what} ({v.name!r}) is neither a tagged sequence nor a "
                f"rank-1 integer lengths vector (shape={v.shape}, "
                f"dtype={v.dtype})", exc=InvalidArgumentError)
        return v


def lod_reset(x, y=None, target_lod=None):
    """≙ reference lod_reset_op: re-tag a tensor with new sequence lengths.
    In the static-shape translation, "LoD" is the companion @SEQLEN length
    vector — resetting means tagging a COPY of `x` with `y`'s lengths (or
    an explicit lengths Variable via target_lod). `x` itself keeps its
    original tagging, matching the reference op's fresh output var."""
    from ..core.dtypes import dtype_name
    from ..layer_helper import LayerHelper
    enforce(y is not None or target_lod is not None,
            "lod_reset needs y (a tagged sequence or lengths var) or "
            "target_lod", exc=InvalidArgumentError)
    lengths = _as_lengths_var(y if y is not None else target_lod,
                              "lod_reset lengths source")
    helper = LayerHelper("lod_reset")
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype),
                                     shape=list(x.shape))
    helper.append_op(type="assign", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return tag_sequence(out, lengths)


def max_sequence_len(rank_table_or_seq):
    """≙ max_sequence_len_op (over a lod_rank_table in the reference): the
    longest sequence length in the batch. Accepts a tagged sequence or a
    rank-1 integer lengths vector."""
    from . import nn as _nn
    lengths = _as_lengths_var(rank_table_or_seq, "max_sequence_len input")
    return _nn.reduce_max(lengths)
