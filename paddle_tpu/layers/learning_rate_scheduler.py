"""In-graph learning-rate decay schedules.

Capability equivalent of the reference's
python/paddle/fluid/layers/learning_rate_scheduler.py (noam_decay,
exponential_decay, natural_exp_decay, inverse_time_decay, polynomial_decay,
piecewise_decay — each built as ops inside the main program over an
auto-incremented global step counter). On TPU the whole schedule fuses into
the compiled train step; the counter is a persistable [1] float var updated
in place via donated buffers.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..core import unique_name
from ..framework.program import (Variable, default_main_program,
                                 default_startup_program)
from ..layer_helper import LayerHelper
from . import ops as unary_ops
from .math_ops import scale
from .nn import elementwise_max, elementwise_min
from .tensor import fill_constant

__all__ = [
    "autoincreased_step_counter", "noam_decay", "exponential_decay",
    "natural_exp_decay", "inverse_time_decay", "polynomial_decay",
    "piecewise_decay", "cosine_decay",
]


def autoincreased_step_counter(counter_name: Optional[str] = None,
                               begin: int = 1, step: int = 1) -> Variable:
    """Global step counter, incremented in place once per executed step
    (≙ reference layers/nn.py autoincreased_step_counter). int64 so long
    runs never hit the float32 2^24 increment plateau."""
    name = counter_name or unique_name.generate("@STEP_COUNTER@")
    main_block = default_main_program().global_block()
    if name in main_block.vars:
        existing = main_block.vars[name]
        prev = getattr(existing, "_counter_begin_step", None)
        if prev is not None and prev != (begin, step):
            raise ValueError(
                f"step counter {name!r} already created with "
                f"(begin, step)={prev}, requested {(begin, step)}; use a "
                f"distinct counter_name per schedule")
        return existing
    counter = main_block.create_var(name=name, shape=[1], dtype="int64",
                                    persistable=True)
    counter.stop_gradient = True
    counter._counter_begin_step = (begin, step)
    sb = default_startup_program().global_block()
    sv = sb.create_var(name=name, shape=[1], dtype="int64",
                       persistable=True)
    sb.append_op("fill_constant", outputs={"Out": [sv.name]},
                 attrs={"shape": [1], "value": float(begin - step),
                        "dtype": "int64"})
    main_block.append_op("increment", inputs={"X": [counter.name]},
                         outputs={"Out": [counter.name]},
                         attrs={"step": float(step)})
    return counter


def _decay_step_counter(begin: int = 0) -> Variable:
    from .tensor import cast
    counter = autoincreased_step_counter(
        counter_name=f"@LR_DECAY_COUNTER@{begin}@", begin=begin, step=1)
    step = cast(counter, "float32")
    step.stop_gradient = True
    return step


def noam_decay(d_model: float, warmup_steps: float) -> Variable:
    """lr = d_model^-0.5 * min(step^-0.5, step * warmup_steps^-1.5)
    (≙ reference learning_rate_scheduler.py noam_decay)."""
    step = _decay_step_counter(begin=1)
    a = unary_ops.pow(step, factor=-0.5)
    b = scale(step, float(warmup_steps) ** -1.5)
    lr = scale(elementwise_min(a, b), float(d_model) ** -0.5)
    lr.stop_gradient = True
    return lr


def exponential_decay(learning_rate: float, decay_steps: int,
                      decay_rate: float, staircase: bool = False) -> Variable:
    """lr * decay_rate^(step/decay_steps) (floored when staircase)."""
    step = _decay_step_counter()
    ratio = scale(step, 1.0 / float(decay_steps))
    if staircase:
        ratio = unary_ops.floor(ratio)
    rate = fill_constant(shape=[1], dtype="float32", value=float(decay_rate))
    lr = scale(rate ** ratio, float(learning_rate))
    lr.stop_gradient = True
    return lr


def natural_exp_decay(learning_rate: float, decay_steps: int,
                      decay_rate: float, staircase: bool = False) -> Variable:
    """lr * exp(-decay_rate * step/decay_steps)."""
    step = _decay_step_counter()
    ratio = scale(step, 1.0 / float(decay_steps))
    if staircase:
        ratio = unary_ops.floor(ratio)
    lr = scale(unary_ops.exp(scale(ratio, -float(decay_rate))),
               float(learning_rate))
    lr.stop_gradient = True
    return lr


def inverse_time_decay(learning_rate: float, decay_steps: int,
                       decay_rate: float, staircase: bool = False) -> Variable:
    """lr / (1 + decay_rate * step/decay_steps)."""
    step = _decay_step_counter()
    ratio = scale(step, 1.0 / float(decay_steps))
    if staircase:
        ratio = unary_ops.floor(ratio)
    denom = scale(ratio, float(decay_rate), 1.0)
    lr = scale(unary_ops.reciprocal(denom), float(learning_rate))
    lr.stop_gradient = True
    return lr


def polynomial_decay(learning_rate: float, decay_steps: int,
                     end_learning_rate: float = 0.0001, power: float = 1.0,
                     cycle: bool = False) -> Variable:
    """(lr - end_lr) * (1 - step/decay_steps)^power + end_lr
    (≙ reference learning_rate_scheduler.py polynomial_decay, incl. the
    cycle mode that stretches decay_steps to the next multiple)."""
    step = _decay_step_counter()
    if cycle:
        div = unary_ops.ceil(scale(step, 1.0 / float(decay_steps)))
        # at step 0 the reference forces div=1 so lr starts at learning_rate
        one = fill_constant(shape=[1], dtype="float32", value=1.0)
        div = elementwise_max(div, one)
        decay_steps_var = scale(div, float(decay_steps))
        ratio = step / decay_steps_var
    else:
        limit = fill_constant(shape=[1], dtype="float32",
                              value=float(decay_steps))
        step = elementwise_min(step, limit)
        ratio = scale(step, 1.0 / float(decay_steps))
    base = scale(ratio, -1.0, 1.0)  # 1 - step/decay_steps
    lr = scale(unary_ops.pow(base, factor=float(power)),
               float(learning_rate) - float(end_learning_rate),
               float(end_learning_rate))
    lr.stop_gradient = True
    return lr


def piecewise_decay(boundaries: Sequence[int],
                    values: Sequence[float]) -> Variable:
    """Piecewise-constant schedule (≙ reference piecewise_decay, which builds
    a Switch; here a single searchsorted-style op, branch-free on TPU)."""
    if len(values) != len(boundaries) + 1:
        raise ValueError("len(values) must be len(boundaries) + 1")
    step = _decay_step_counter()
    helper = LayerHelper("piecewise_decay")
    lr = helper.create_tmp_variable(dtype="float32", shape=[1],
                                    stop_gradient=True)
    helper.append_op(type="piecewise_decay", inputs={"Step": [step]},
                     outputs={"Out": [lr]},
                     attrs={"boundaries": [float(b) for b in boundaries],
                            "values": [float(v) for v in values]})
    lr.stop_gradient = True
    return lr


def cosine_decay(learning_rate: float, step_each_epoch: int,
                 epochs: int) -> Variable:
    """lr * 0.5 * (cos(pi * epoch / epochs) + 1) — cosine annealing over
    whole epochs (staircase per epoch, as in later reference versions)."""
    import math
    step = _decay_step_counter()
    epoch = unary_ops.floor(scale(step, 1.0 / float(step_each_epoch)))
    inner = scale(epoch, math.pi / float(epochs))
    lr = scale(unary_ops.cos(inner), 0.5 * float(learning_rate),
               0.5 * float(learning_rate))
    lr.stop_gradient = True
    return lr
