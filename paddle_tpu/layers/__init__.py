"""fluid.layers-equivalent namespace (≙ reference python/paddle/fluid/layers/)."""

from . import io, math_ops, nn, ops, tensor  # noqa: F401
from .io import data  # noqa: F401
from .math_ops import scale  # noqa: F401
from .nn import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .tensor import (argmax, argmin, argsort, assign, cast, concat,  # noqa: F401
                     create_tensor, fill_constant,
                     fill_constant_batch_size_like, ones, reverse, sums,
                     zeros, zeros_like)
