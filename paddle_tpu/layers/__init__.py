"""fluid.layers-equivalent namespace (≙ reference python/paddle/fluid/layers/)."""

from . import (control_flow, detection, device, io,  # noqa: F401
               learning_rate_scheduler, math_ops, nn, ops, sequence, tensor)
from .learning_rate_scheduler import (autoincreased_step_counter,  # noqa: F401
                                      cosine_decay, exponential_decay,
                                      inverse_time_decay, natural_exp_decay,
                                      noam_decay, piecewise_decay,
                                      polynomial_decay)
from .control_flow import (DynamicRNN, IfElse, StaticRNN, Switch,  # noqa: F401
                           While, cond, equal, greater_equal, greater_than,
                           increment, less_equal, less_than, not_equal)
from .device import get_places  # noqa: F401
from .io import batch_row_mask, data  # noqa: F401
from .sequence import (chunk_eval, crf_decoding,  # noqa: F401
                       ctc_greedy_decoder, dynamic_gru, dynamic_lstm,
                       linear_chain_crf, sequence_concat,
                       sequence_conv, sequence_erase, sequence_expand,
                       sequence_first_step, sequence_last_step, sequence_mask,
                       sequence_pad,
                       sequence_pool, sequence_reverse, sequence_slice,
                       sequence_softmax, warpctc)
from .math_ops import scale  # noqa: F401
from .nn import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .tensor import (argmax, argmin, argsort, assign, cast, concat,  # noqa: F401
                     create_tensor, fill_constant,
                     fill_constant_batch_size_like, ones, reverse, sums,
                     zeros, zeros_like)
