"""Control-flow layers: While, StaticRNN, DynamicRNN, IfElse, Switch, cond.

≙ reference python/paddle/fluid/layers/control_flow.py (While:655,
StaticRNN:430, DynamicRNN:1542, IfElse:1412, Switch:1286,
ConditionalBlock:1204). The builders create real sub-blocks in the program
(≙ the BLOCK attr in framework.proto); lowering maps them onto lax.scan /
lax.while_loop / lax.cond / masked-select (see ops/control_ops.py) instead of
the reference's sub-block-interpreting C++ ops.

TPU notes:
- StaticRNN/DynamicRNN are lax.scan: differentiable, compiler-scheduled.
- While is lax.while_loop: forward-only (XLA while has no reverse-mode);
  use the RNN classes for trainable recurrences.
- IfElse runs both branches and mask-merges (static shapes) — the
  TPU translation of the reference's split-batch-by-condition gather.
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional, Sequence

from ..core.dtypes import dtype_name
from ..core.enforce import InvalidArgumentError, enforce
from ..framework.program import Variable, default_main_program
from ..layer_helper import LayerHelper


def _analyze_sub_block(block, exclude_inner: Sequence[str] = ()):
    """(reads-from-outside, writes) of a sub-block."""
    produced = set(exclude_inner)
    reads: List[str] = []
    writes: List[str] = []
    for op in block.ops:
        for n in op.input_names():
            if n not in produced and n not in reads:
                reads.append(n)
        for n in op.output_names():
            produced.add(n)
            if n not in writes:
                writes.append(n)
    return reads, writes


class While:
    """≙ fluid.layers.While (reference control_flow.py:655).

    cond: scalar bool variable. Vars assigned in the body that pre-exist
    outside become loop-carried state (their post-loop values are visible
    after the loop). Forward-only on TPU (see module docstring).

        i = layers.fill_constant([1], "int64", 0)
        n = layers.fill_constant([1], "int64", 10)
        cond = layers.less_than(i, n)
        w = While(cond)
        with w.block():
            ... body ops, must re-assign `cond` ...
    """

    def __init__(self, cond: Variable, name: Optional[str] = None):
        enforce(cond.dtype is not None, "cond must be a bool variable",
                exc=InvalidArgumentError)
        self.cond = cond
        self.helper = LayerHelper("while", name=name)
        self.program = default_main_program()

    @contextlib.contextmanager
    def block(self):
        parent = self.program.current_block()
        sub = self.program._create_block()
        try:
            yield
        finally:
            self.program._rollback()
        reads, writes = _analyze_sub_block(sub)
        # loop-carried: cond + every var written in the body that exists
        # outside the body (same-name update semantics, ≙ while_op's
        # in-place scope vars)
        carry = [self.cond.name]
        for n in writes:
            if n != self.cond.name and parent.has_var(n) and n not in carry:
                carry.append(n)
        captures = [n for n in reads
                    if n not in carry and parent.has_var(n)]
        parent.append_op(
            type="while",
            inputs={"Carry": list(carry), "Captures": captures},
            outputs={"Out": list(carry)},
            attrs={"sub_block": sub.idx, "carry_names": list(carry),
                   "capture_names": captures, "cond_name": self.cond.name})


class StaticRNN:
    """≙ fluid.layers.StaticRNN (reference control_flow.py:430): explicit
    per-step block over a fixed-length (padded) time dimension, lowered to
    one lax.scan."""

    def __init__(self, name: Optional[str] = None):
        self.helper = LayerHelper("static_rnn", name=name)
        self.program = default_main_program()
        self._step_inputs: List[Variable] = []   # outer [B,T,...] vars
        self._step_vars: List[Variable] = []     # inner per-step views
        self._memories: List[Variable] = []      # inner pre-state vars
        self._init_mems: List[Variable] = []     # outer init values
        self._mem_updates: Dict[str, str] = {}   # pre name -> new name
        self._step_outputs: List[Variable] = []  # inner step outputs
        self._outer_outputs: List[Variable] = []
        self._seq_lens: Optional[Variable] = None
        self._sub = None
        self._parent = None
        self._reverse = False

    @contextlib.contextmanager
    def step(self):
        self._parent = self.program.current_block()
        self._sub = self.program._create_block()
        try:
            yield
        except BaseException:
            # a failing step body must surface ITS error — finalizing a
            # half-built block would mask it behind "every memory needs
            # update_memory"
            self.program._rollback()
            raise
        self.program._rollback()
        self._finalize()

    # -- inside-step API --------------------------------------------------
    def step_input(self, x: Variable) -> Variable:
        """Register [B, T, ...] sequence; returns the per-step [B, ...]
        view usable inside the block."""
        enforce(self._sub is not None and
                self.program.current_block() is self._sub,
                "step_input must be called inside rnn.step()",
                exc=InvalidArgumentError)
        v = self._sub.create_var(
            shape=[x.shape[0]] + list(x.shape[2:]),
            dtype=dtype_name(x.dtype), stop_gradient=False)
        self._step_inputs.append(x)
        self._step_vars.append(v)
        return v

    def memory(self, init: Variable) -> Variable:
        """Loop-carried state initialized from `init` [B, ...]."""
        enforce(self.program.current_block() is self._sub,
                "memory must be called inside rnn.step()",
                exc=InvalidArgumentError)
        v = self._sub.create_var(shape=list(init.shape),
                                 dtype=dtype_name(init.dtype),
                                 stop_gradient=False)
        self._memories.append(v)
        self._init_mems.append(init)
        return v

    def update_memory(self, mem: Variable, new: Variable):
        self._mem_updates[mem.name] = new.name

    def step_output(self, out: Variable):
        self._step_outputs.append(out)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def set_sequence_lengths(self, seq_lens: Variable):
        """DynamicRNN behavior: freeze memories & zero outputs past each
        sequence's length."""
        self._seq_lens = seq_lens

    # -- finalize ---------------------------------------------------------
    def _finalize(self):
        enforce(self._step_inputs, "StaticRNN needs at least one step_input",
                exc=InvalidArgumentError)
        enforce(set(self._mem_updates) == {m.name for m in self._memories},
                "every memory needs update_memory", exc=InvalidArgumentError)
        pre_names = [m.name for m in self._memories]
        new_names = [self._mem_updates[n] for n in pre_names]
        inner_defined = set(n for v in self._step_vars for n in [v.name])
        inner_defined |= set(pre_names)
        reads, _ = _analyze_sub_block(self._sub, exclude_inner=inner_defined)
        captures = [n for n in reads if self._parent.has_var(n)]

        t = self._step_inputs[0].shape[1]
        outer_outs = []
        for so in self._step_outputs:
            ov = self._parent.create_var(
                name=None, shape=[so.shape[0], t] + list(so.shape[1:]),
                dtype=dtype_name(so.dtype), stop_gradient=False)
            outer_outs.append(ov)
        final_mems = []
        for m in self._memories:
            fv = self._parent.create_var(name=None, shape=list(m.shape),
                                         dtype=dtype_name(m.dtype),
                                         stop_gradient=False)
            final_mems.append(fv)
        self._outer_outputs = outer_outs
        self._final_mems = final_mems
        inputs = {"StepInputs": [v.name for v in self._step_inputs],
                  "InitMems": [v.name for v in self._init_mems],
                  "Captures": captures}
        if self._seq_lens is not None:
            inputs["SeqLens"] = [self._seq_lens.name]
        self._parent.append_op(
            type="static_rnn",
            inputs=inputs,
            outputs={"Out": [v.name for v in outer_outs],
                     "FinalMems": [v.name for v in final_mems]},
            attrs={"sub_block": self._sub.idx,
                   "step_input_names": [v.name for v in self._step_vars],
                   "pre_mem_names": pre_names,
                   "new_mem_names": new_names,
                   "step_output_names": [o.name for o in self._step_outputs],
                   "capture_names": captures,
                   "is_reverse": self._reverse})

    def __call__(self):
        outs = self._outer_outputs
        return outs[0] if len(outs) == 1 else outs

    def final_memories(self):
        fm = self._final_mems
        return fm[0] if len(fm) == 1 else fm


class DynamicRNN(StaticRNN):
    """≙ fluid.layers.DynamicRNN (reference control_flow.py:1542). On TPU the
    "dynamic" (LoD ragged) batch is the padded+lengths representation: same
    scan as StaticRNN with per-sequence freezing/masking past each length.
    """

    def __init__(self, seq_lens: Optional[Variable] = None, name=None):
        super().__init__(name=name)
        if seq_lens is not None:
            self.set_sequence_lengths(seq_lens)

    @contextlib.contextmanager
    def block(self):
        with self.step():
            yield

    def static_input(self, x: Variable) -> Variable:
        """Non-sequence input visible every step (captured)."""
        return x

    def step_input(self, x: Variable) -> Variable:
        if self._seq_lens is None:
            from .sequence import get_seqlen
            sl = getattr(x, "seqlen_var", None)
            if sl is None:
                try:
                    sl = get_seqlen(x)
                except Exception:
                    sl = None
            if sl is not None:
                self.set_sequence_lengths(sl)
        return super().step_input(x)


class IfElse:
    """≙ fluid.layers.IfElse (reference control_flow.py:1412): batched
    two-branch conditional. Both branches compute on the full batch and
    results merge elementwise by the [B, 1] bool condition."""

    def __init__(self, cond: Variable, name: Optional[str] = None):
        self.cond = cond
        self.program = default_main_program()
        self._blocks = {}          # True/False -> block
        self._outs = {True: [], False: []}
        self._parent = None

    @contextlib.contextmanager
    def true_block(self):
        with self._branch(True):
            yield

    @contextlib.contextmanager
    def false_block(self):
        with self._branch(False):
            yield

    @contextlib.contextmanager
    def _branch(self, is_true: bool):
        self._parent = self.program.current_block()
        sub = self.program._create_block()
        self._blocks[is_true] = sub
        self._in_branch = is_true
        try:
            yield
        finally:
            self.program._rollback()
            self._in_branch = None

    def input(self, x: Variable) -> Variable:
        """In the reference this gathers the branch's subset; here the full
        batch flows through both branches (mask-merge at output)."""
        return x

    def output(self, *outs):
        enforce(self._in_branch is not None,
                "IfElse.output must be called inside a branch block",
                exc=InvalidArgumentError)
        self._outs[self._in_branch].extend(outs)

    def __call__(self):
        enforce(True in self._blocks and False in self._blocks,
                "both true_block and false_block are required",
                exc=InvalidArgumentError)
        t_outs = self._outs[True]
        f_outs = self._outs[False]
        enforce(len(t_outs) == len(f_outs) and t_outs,
                "branches must produce the same number of outputs",
                exc=InvalidArgumentError)
        t_reads, _ = _analyze_sub_block(self._blocks[True])
        f_reads, _ = _analyze_sub_block(self._blocks[False])
        captures = []
        for n in t_reads + f_reads:
            if n not in captures and self._parent.has_var(n):
                captures.append(n)
        merged = []
        for tv in t_outs:
            merged.append(self._parent.create_var(
                shape=list(tv.shape), dtype=dtype_name(tv.dtype),
                stop_gradient=False))
        self._parent.append_op(
            type="cond_block",
            inputs={"Cond": [self.cond.name], "Captures": captures},
            outputs={"Out": [v.name for v in merged]},
            attrs={"true_block": self._blocks[True].idx,
                   "false_block": self._blocks[False].idx,
                   "capture_names": captures,
                   "true_out_names": [v.name for v in t_outs],
                   "false_out_names": [v.name for v in f_outs]})
        return merged  # always a list, like the reference IfElse()()


def cond(pred: Variable, true_fn, false_fn):
    """Functional scalar conditional (lax.cond — one branch executes).
    true_fn/false_fn build ops and return a Variable (or list)."""
    program = default_main_program()
    parent = program.current_block()

    def build(fn):
        sub = program._create_block()
        try:
            out = fn()
        finally:
            program._rollback()
        outs = out if isinstance(out, (list, tuple)) else [out]
        return sub, list(outs)

    t_sub, t_outs = build(true_fn)
    f_sub, f_outs = build(false_fn)
    enforce(len(t_outs) == len(f_outs),
            "cond branches must return the same number of outputs",
            exc=InvalidArgumentError)
    t_reads, _ = _analyze_sub_block(t_sub)
    f_reads, _ = _analyze_sub_block(f_sub)
    captures = []
    for n in t_reads + f_reads:
        if n not in captures and parent.has_var(n):
            captures.append(n)
    merged = [parent.create_var(shape=list(tv.shape),
                                dtype=dtype_name(tv.dtype),
                                stop_gradient=False)
              for tv in t_outs]
    parent.append_op(
        type="lazy_cond",
        inputs={"Cond": [pred.name], "Captures": captures},
        outputs={"Out": [v.name for v in merged]},
        attrs={"true_block": t_sub.idx, "false_block": f_sub.idx,
               "capture_names": captures,
               "true_out_names": [v.name for v in t_outs],
               "false_out_names": [v.name for v in f_outs]})
    return merged[0] if len(merged) == 1 else merged


class Switch:
    """≙ fluid.layers.Switch (reference control_flow.py:1286) — the lr
    scheduler's piecewise construct. Each case block assigns a value to a
    target variable; first true condition wins, default block otherwise."""

    def __init__(self, name: Optional[str] = None):
        self.program = default_main_program()
        self._conds: List[Variable] = []
        self._case_blocks = []
        self._case_out_names: List[str] = []
        self._parent = None
        self._target: Optional[Variable] = None

    @contextlib.contextmanager
    def case(self, condition: Variable):
        self._conds.append(condition)
        with self._case_ctx():
            yield

    @contextlib.contextmanager
    def default(self):
        with self._case_ctx():
            yield

    @contextlib.contextmanager
    def _case_ctx(self):
        if self._parent is None:
            self._parent = self.program.current_block()
        sub = self.program._create_block()
        try:
            yield
        finally:
            self.program._rollback()
        enforce(sub.ops, "empty Switch case", exc=InvalidArgumentError)
        last = sub.ops[-1]
        out_names = last.output_names()
        enforce(len(out_names) >= 1, "case block must produce a value",
                exc=InvalidArgumentError)
        self._case_blocks.append(sub)
        self._case_out_names.append(out_names[0])
        # target var: by convention all cases assign the same outer var
        if self._target is None and self._parent.has_var(out_names[0]):
            self._target = self._parent.var(out_names[0])

    def finish(self, out: Optional[Variable] = None) -> Variable:
        """Merge cases. If the cases assigned an outer var (reference
        `assign` style) the merged value lands back in it."""
        parent = self._parent
        captures = []
        for b in self._case_blocks:
            reads, _ = _analyze_sub_block(b)
            for n in reads:
                if n not in captures and parent.has_var(n):
                    captures.append(n)
        target = out or self._target
        inputs = {"Conds": [c.name for c in self._conds],
                  "Captures": captures}
        if target is None:
            first = self._case_blocks[0]
            proto = first.var(self._case_out_names[0])
            target = parent.create_var(shape=list(proto.shape),
                                      dtype=dtype_name(proto.dtype),
                                      stop_gradient=False)
        elif target.op is not None or target.is_data:
            # no-default fallback: keep the target's pre-switch value
            inputs["Prev"] = [target.name]
        parent.append_op(
            type="switch_case",
            inputs=inputs,
            outputs={"Out": [target.name]},
            attrs={"case_blocks": [b.idx for b in self._case_blocks],
                   "case_out_names": list(self._case_out_names),
                   "capture_names": captures})
        return target


# ---- scalar/compare/step helper layers (≙ reference control_flow.py
#      increment:?, less_than, array ops region :741-1148) ----------------

def increment(x: Variable, value: float = 1.0, in_place: bool = False,
              name: Optional[str] = None) -> Variable:
    helper = LayerHelper("increment", name=name)
    if in_place:
        out = x
    else:
        out = helper.create_tmp_variable(dtype=dtype_name(x.dtype),
                                         shape=x.shape)
    helper.append_op(type="increment", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"step": float(value)})
    return out


def _compare(op_type, x, y, cond=None):
    from .math_ops import _broadcast_shape
    helper = LayerHelper(op_type)
    if cond is None:
        # declared shape must be the broadcast of both operands (the old
        # x.shape under-declared broadcast dims — flagged by the static
        # analyzer, framework/analysis.py)
        cond = helper.create_tmp_variable(
            dtype="bool", shape=_broadcast_shape(x.shape, y.shape),
            stop_gradient=True)
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def less_than(x, y, cond=None):
    return _compare("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _compare("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _compare("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _compare("greater_equal", x, y, cond)


def equal(x, y, cond=None):
    return _compare("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _compare("not_equal", x, y, cond)


# ---------------------------------------------------------------------------
# Tensor arrays (≙ reference layers/control_flow.py array ops :741-1148:
# create_array / array_write / array_read / array_length over
# LoDTensorArray). Static-shape translation: an "array" is a preallocated
# [max_len, ...] dense var; writes are functional index updates. The
# reference's dynamically-growing arrays need an interpreting executor;
# under XLA the capacity is declared up front.
# ---------------------------------------------------------------------------

def create_array(dtype, initial_value=0.0, max_len=None, shape=None,
                 name=None):
    """Preallocate a [max_len, *shape] array var (≙ create_array; the extra
    max_len/shape args are the static-shape contract)."""
    from ..layer_helper import LayerHelper
    enforce(max_len is not None and shape is not None,
            "create_array on TPU needs static max_len and element shape",
            exc=InvalidArgumentError)
    enforce(int(max_len) > 0, "create_array needs max_len >= 1",
            exc=InvalidArgumentError)
    enforce(all(int(d) > 0 for d in shape),
            "create_array element shape must be fully static (no -1): "
            "preallocated arrays cannot defer dims to feed time",
            exc=InvalidArgumentError)
    helper = LayerHelper("create_array", name=name)
    out = helper.create_tmp_variable(dtype=dtype,
                                     shape=[int(max_len)] + list(shape))
    helper.append_op(type="fill_constant", inputs={},
                     outputs={"Out": [out]},
                     attrs={"shape": [int(max_len)] + list(shape),
                            "dtype": dtype, "value": float(initial_value)})
    return out


def array_write(x, i, array):
    """Functional write: returns the UPDATED array var (≙ array_write;
    callers thread the returned var, matching the functional executor)."""
    from ..core.dtypes import dtype_name
    from ..layer_helper import LayerHelper
    helper = LayerHelper("array_write")
    out = helper.create_tmp_variable(dtype=dtype_name(array.dtype),
                                     shape=list(array.shape))
    helper.append_op(type="array_write",
                     inputs={"Array": [array], "X": [x], "I": [i]},
                     outputs={"Out": [out]})
    return out


def array_read(array, i):
    """≙ array_read: the element at index i."""
    from ..core.dtypes import dtype_name
    from ..layer_helper import LayerHelper
    helper = LayerHelper("array_read")
    out = helper.create_tmp_variable(dtype=dtype_name(array.dtype),
                                     shape=list(array.shape[1:]))
    helper.append_op(type="array_read",
                     inputs={"Array": [array], "I": [i]},
                     outputs={"Out": [out]})
    return out


def array_length(array):
    """≙ array_length: the (static) capacity of the array."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper("array_length")
    out = helper.create_tmp_variable(dtype="int64", shape=[])
    helper.append_op(type="array_length", inputs={"X": [array]},
                     outputs={"Out": [out]})
    return out
