"""Detection layers: SSD pipeline + RPN/ROI building blocks.

≙ reference python/paddle/fluid/layers/detection.py (prior_box,
multi_box_head, bipartite_match, target_assign, ssd_loss, detection_output,
iou_similarity, box_coder, anchor_generator) and layers roi_pool. The
reference's LoD'd ground-truth batches become dense [B, G, ...] tensors with
a gt_count-style validity encoded as zero-area boxes; all matching/NMS loops
compile to fixed-shape lax loops (see ops/detection_ops.py).
"""

from __future__ import annotations

from ..core.dtypes import dtype_name
from ..core.enforce import InvalidArgumentError, enforce
from ..layer_helper import LayerHelper
from . import nn as _nn
from .tensor import concat

__all__ = [
    "prior_box", "density_prior_box", "anchor_generator", "iou_similarity",
    "box_coder", "bipartite_match", "target_assign", "multiclass_nms",
    "detection_output", "ssd_loss", "roi_pool", "multi_box_head",
    "rpn_target_assign", "generate_proposals", "detection_map",
]


def _tmp(helper, dtype, shape):
    return helper.create_tmp_variable(dtype=dtype, shape=shape)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    """≙ reference layers/detection.py prior_box. Returns (boxes, variances)
    of shape [H, W, P, 4]."""
    from ..ops.detection_ops import expand_aspect_ratios
    helper = LayerHelper("prior_box", name=name)
    fh, fw = input.shape[2], input.shape[3]
    n_ar = len(expand_aspect_ratios(aspect_ratios, flip))
    P = len(min_sizes) * n_ar + (len(max_sizes) if max_sizes else 0)
    dtype = dtype_name(input.dtype)
    boxes = _tmp(helper, dtype, [fh, fw, P, 4])
    variances = _tmp(helper, dtype, [fh, fw, P, 4])
    helper.append_op(
        type="prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [variances]},
        attrs={"min_sizes": list(min_sizes),
               "max_sizes": list(max_sizes or []),
               "aspect_ratios": list(aspect_ratios),
               "variances": list(variance), "flip": flip, "clip": clip,
               "step_w": steps[0], "step_h": steps[1], "offset": offset})
    return boxes, variances


def density_prior_box(input, image, densities, fixed_sizes,
                      fixed_ratios=(1.0,), variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5, name=None):
    """≙ reference layers/detection.py density_prior_box."""
    helper = LayerHelper("density_prior_box", name=name)
    fh, fw = input.shape[2], input.shape[3]
    P = sum(d * d * len(fixed_ratios) for d in densities)
    dtype = dtype_name(input.dtype)
    boxes = _tmp(helper, dtype, [fh, fw, P, 4])
    variances = _tmp(helper, dtype, [fh, fw, P, 4])
    helper.append_op(
        type="density_prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [variances]},
        attrs={"densities": list(densities),
               "fixed_sizes": list(fixed_sizes),
               "fixed_ratios": list(fixed_ratios),
               "variances": list(variance), "clip": clip,
               "step_w": steps[0], "step_h": steps[1], "offset": offset})
    return boxes, variances


def anchor_generator(input, anchor_sizes, aspect_ratios, stride,
                     variance=(0.1, 0.1, 0.2, 0.2), offset=0.5, name=None):
    """≙ reference layers/detection.py anchor_generator (RPN)."""
    helper = LayerHelper("anchor_generator", name=name)
    fh, fw = input.shape[2], input.shape[3]
    P = len(anchor_sizes) * len(aspect_ratios)
    dtype = dtype_name(input.dtype)
    anchors = _tmp(helper, dtype, [fh, fw, P, 4])
    variances = _tmp(helper, dtype, [fh, fw, P, 4])
    helper.append_op(
        type="anchor_generator", inputs={"Input": [input]},
        outputs={"Anchors": [anchors], "Variances": [variances]},
        attrs={"anchor_sizes": list(anchor_sizes),
               "aspect_ratios": list(aspect_ratios),
               "stride": list(stride), "variances": list(variance),
               "offset": offset})
    return anchors, variances


def iou_similarity(x, y, name=None):
    """≙ reference layers iou_similarity: [N,4]x[M,4] -> [N,M]."""
    helper = LayerHelper("iou_similarity", name=name)
    shape = list(x.shape[:-1]) + [y.shape[0]]
    out = _tmp(helper, dtype_name(x.dtype), shape)
    helper.append_op(type="iou_similarity", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None):
    """≙ reference layers box_coder."""
    helper = LayerHelper("box_coder", name=name)
    m = prior_box.shape[0]
    if code_type == "encode_center_size":
        shape = [target_box.shape[0], m, 4]
    else:
        shape = list(target_box.shape)
    out = _tmp(helper, dtype_name(target_box.dtype), shape)
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(type="box_coder", inputs=inputs,
                     outputs={"OutputBox": [out]},
                     attrs={"code_type": code_type,
                            "box_normalized": box_normalized})
    return out


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    """≙ reference layers bipartite_match. Returns
    (match_indices, match_distance)."""
    helper = LayerHelper("bipartite_match", name=name)
    shape = list(dist_matrix.shape[:-2]) + [dist_matrix.shape[-1]]
    idx = _tmp(helper, "int32", shape)
    dist = _tmp(helper, dtype_name(dist_matrix.dtype), shape)
    helper.append_op(type="bipartite_match",
                     inputs={"DistMat": [dist_matrix]},
                     outputs={"ColToRowMatchIndices": [idx],
                              "ColToRowMatchDist": [dist]},
                     attrs={"match_type": match_type,
                            "dist_threshold": dist_threshold})
    return idx, dist


def target_assign(input, matched_indices, mismatch_value=0, name=None):
    """≙ reference layers target_assign. Returns (out, out_weight)."""
    helper = LayerHelper("target_assign", name=name)
    b, m = matched_indices.shape[0], matched_indices.shape[1]
    k = input.shape[-1]
    out = _tmp(helper, dtype_name(input.dtype), [b, m, k])
    w = _tmp(helper, "float32", [b, m, 1])
    helper.append_op(type="target_assign",
                     inputs={"X": [input],
                             "MatchIndices": [matched_indices]},
                     outputs={"Out": [out], "OutWeight": [w]},
                     attrs={"mismatch_value": mismatch_value})
    return out, w


def multiclass_nms(bboxes, scores, score_threshold=0.01, nms_top_k=400,
                   keep_top_k=200, nms_threshold=0.3, background_label=0,
                   normalized=True, name=None):
    """≙ reference multiclass_nms. Returns (out [B,keep_top_k,6], rois_num
    [B]) — padded rows carry label -1 (static translation of the LoD out)."""
    helper = LayerHelper("multiclass_nms", name=name)
    b = scores.shape[0]
    out = _tmp(helper, "float32", [b, keep_top_k, 6])
    num = _tmp(helper, "int32", [b])
    helper.append_op(type="multiclass_nms",
                     inputs={"BBoxes": [bboxes], "Scores": [scores]},
                     outputs={"Out": [out], "NmsRoisNum": [num]},
                     attrs={"score_threshold": score_threshold,
                            "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                            "nms_threshold": nms_threshold,
                            "background_label": background_label})
    return out, num


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, name=None):
    """≙ reference detection_output: decode loc offsets against priors then
    multiclass NMS. loc [B,M,4] offsets, scores [B,C,M] (softmaxed or raw
    probabilities). Returns (out, rois_num)."""
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    return multiclass_nms(decoded, scores,
                          score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold,
                          background_label=background_label, name=name)


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, name=None):
    """≙ reference layers roi_pool. rois [R,5] (batch_idx,x1,y1,x2,y2)."""
    helper = LayerHelper("roi_pool", name=name)
    r = rois.shape[0]
    c = input.shape[1]
    out = _tmp(helper, dtype_name(input.dtype),
               [r, c, pooled_height, pooled_width])
    helper.append_op(type="roi_pool",
                     inputs={"X": [input], "ROIs": [rois]},
                     outputs={"Out": [out]},
                     attrs={"pooled_height": pooled_height,
                            "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale})
    return out


def multi_box_head(inputs, image, num_classes, min_sizes, max_sizes=None,
                   aspect_ratios=None, steps=None, offset=0.5, flip=True,
                   clip=False, name=None):
    """≙ reference multi_box_head: per-feature-map conv heads emitting loc
    offsets + class scores over generated priors.

    inputs: list of feature maps [N,C,H,W]. Returns
    (mbox_locs [B,M,4], mbox_confs [B,M,C] raw logits — softmax +
    transpose to [B,C,M] before detection_output/multiclass_nms —,
    boxes [M,4], variances [M,4])."""
    enforce(len(inputs) == len(min_sizes), "one min_size per input",
            exc=InvalidArgumentError)
    enforce(max_sizes is None or len(max_sizes) == len(inputs),
            "one max_size per input", exc=InvalidArgumentError)
    enforce(steps is None or len(steps) == len(inputs),
            "one step per input", exc=InvalidArgumentError)
    aspect_ratios = aspect_ratios or [[1.0]] * len(inputs)
    enforce(len(aspect_ratios) == len(inputs),
            "one aspect_ratio list per input", exc=InvalidArgumentError)
    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, feat in enumerate(inputs):
        ms = min_sizes[i] if isinstance(min_sizes[i], (list, tuple)) \
            else [min_sizes[i]]
        mx = None
        if max_sizes:
            mx = max_sizes[i] if isinstance(max_sizes[i], (list, tuple)) \
                else [max_sizes[i]]
        step = steps[i] if steps else (0.0, 0.0)
        if not isinstance(step, (list, tuple)):
            step = (float(step), float(step))
        box, var = prior_box(feat, image, ms, mx, aspect_ratios[i],
                             flip=flip, clip=clip, steps=step, offset=offset)
        p = box.shape[2]
        m_i = box.shape[0] * box.shape[1] * p
        loc = _nn.conv2d(feat, num_filters=p * 4, filter_size=3, padding=1,
                         name=name and f"{name}_loc{i}")
        loc = _nn.transpose(loc, perm=[0, 2, 3, 1])
        loc = _nn.reshape(loc, shape=[-1, m_i, 4])
        conf = _nn.conv2d(feat, num_filters=p * num_classes, filter_size=3,
                          padding=1, name=name and f"{name}_conf{i}")
        conf = _nn.transpose(conf, perm=[0, 2, 3, 1])
        conf = _nn.reshape(conf, shape=[-1, m_i, num_classes])
        locs.append(loc)
        confs.append(conf)
        boxes_all.append(_nn.reshape(box, shape=[m_i, 4]))
        vars_all.append(_nn.reshape(var, shape=[m_i, 4]))
    mbox_locs = concat(locs, axis=1)                 # [B, M, 4]
    mbox_confs = concat(confs, axis=1)               # [B, M, C]
    boxes = concat(boxes_all, axis=0)                # [M, 4]
    variances = concat(vars_all, axis=0)
    return mbox_locs, mbox_confs, boxes, variances


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, loc_loss_weight=1.0, conf_loss_weight=1.0,
             mismatch_value=0, name=None):
    """SSD multibox loss (≙ reference layers/detection.py ssd_loss):
    match priors to ground truth (bipartite + per-prediction), encode box
    targets, smooth-L1 localization loss on positives, softmax confidence
    loss with hard negative mining at neg_pos_ratio.

    location [B,M,4]; confidence [B,M,C] raw logits; gt_box [B,G,4]
    (zero-area rows = padding); gt_label [B,G] int; prior_box [M,4].
    Returns the scalar loss.
    """
    helper = LayerHelper("ssd_loss", name=name)
    dtype = dtype_name(location.dtype)
    loss = _tmp(helper, dtype, [])
    inputs = {"Location": [location], "Confidence": [confidence],
              "GTBox": [gt_box], "GTLabel": [gt_label],
              "PriorBox": [prior_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(type="ssd_loss", inputs=inputs,
                     outputs={"Loss": [loss]},
                     attrs={"background_label": background_label,
                            "overlap_threshold": overlap_threshold,
                            "neg_pos_ratio": neg_pos_ratio,
                            "loc_loss_weight": loc_loss_weight,
                            "conf_loss_weight": conf_loss_weight,
                            "mismatch_value": mismatch_value})
    return loss


def rpn_target_assign(anchor_box, gt_box, rpn_batch_size_per_im=256,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, name=None):
    """≙ reference layers/detection.py rpn_target_assign
    (rpn_target_assign_op.cc). Static-shape form: returns per-anchor
    (labels, box_deltas, box_inside_weight) — labels [N] in {-1 ignore,
    0 bg, 1 fg}, deltas/weights [N, 4] — instead of gathered index lists
    (dynamic shapes don't compile on TPU)."""
    helper = LayerHelper("rpn_target_assign", name=name)
    n = anchor_box.shape[0]
    dtype = dtype_name(anchor_box.dtype)
    labels = _tmp(helper, "int32", [n])
    deltas = _tmp(helper, dtype, [n, 4])
    inside_w = _tmp(helper, dtype, [n, 4])
    helper.append_op(type="rpn_target_assign",
                     inputs={"Anchor": [anchor_box], "GtBox": [gt_box]},
                     outputs={"Labels": [labels], "BoxDeltas": [deltas],
                              "BoxInsideWeight": [inside_w]},
                     attrs={"rpn_batch_size_per_im": rpn_batch_size_per_im,
                            "rpn_fg_fraction": rpn_fg_fraction,
                            "rpn_positive_overlap": rpn_positive_overlap,
                            "rpn_negative_overlap": rpn_negative_overlap})
    return labels, deltas, inside_w


def generate_proposals(scores, bbox_deltas, im_info, anchors,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, name=None):
    """≙ reference generate_proposals_op.cc. scores [B, A], bbox_deltas
    [B, A, 4], anchors [A, 4], im_info [B, 3] (h, w, scale). Returns
    (rpn_rois [B, post, 4], rpn_roi_probs [B, post, 1],
    rpn_rois_num [B])."""
    helper = LayerHelper("generate_proposals", name=name)
    b = scores.shape[0]
    dtype = dtype_name(scores.dtype)
    rois = _tmp(helper, dtype, [b, post_nms_top_n, 4])
    probs = _tmp(helper, dtype, [b, post_nms_top_n, 1])
    nums = _tmp(helper, "int32", [b])
    helper.append_op(type="generate_proposals",
                     inputs={"Scores": [scores],
                             "BboxDeltas": [bbox_deltas],
                             "ImInfo": [im_info], "Anchors": [anchors]},
                     outputs={"RpnRois": [rois], "RpnRoiProbs": [probs],
                              "RpnRoisNum": [nums]},
                     attrs={"pre_nms_top_n": pre_nms_top_n,
                            "post_nms_top_n": post_nms_top_n,
                            "nms_thresh": nms_thresh, "min_size": min_size})
    return rois, probs, nums


def detection_map(detect_res, label, class_num, overlap_threshold=0.5,
                  ap_version="integral", name=None):
    """≙ reference detection_map_op.cc, IN-graph (the host-side fallback
    lives in metrics.DetectionMAP). detect_res [B, K, 6] rows
    (label, score, box) — the multiclass_nms layout; label (gt) [B, G, 5]
    rows (label, box), zero-area padding. Returns the scalar mAP."""
    enforce(ap_version == "integral",
            "only integral AP is implemented (11point would silently be a "
            "different metric)", exc=InvalidArgumentError)
    helper = LayerHelper("detection_map", name=name)
    m_ap = _tmp(helper, "float32", [1])
    helper.append_op(type="detection_map",
                     inputs={"DetectRes": [detect_res], "Label": [label]},
                     outputs={"MAP": [m_ap]},
                     attrs={"class_num": class_num,
                            "overlap_threshold": overlap_threshold,
                            "ap_type": ap_version})
    return m_ap
