"""Auto-generated thin layer wrappers for activation/unary ops.

≙ reference python/paddle/fluid/layers/ops.py + layer_function_generator.py
(generates ~40 wrappers from registered OpProtos).
"""

from __future__ import annotations

import sys

from ..core.dtypes import dtype_name
from ..layer_helper import LayerHelper

_UNARY_OPS = [
    "sigmoid", "logsigmoid", "exp", "tanh", "tanh_shrink", "sqrt", "rsqrt",
    "abs", "ceil", "floor", "cos", "sin", "round", "reciprocal", "log",
    "square", "relu", "relu6", "softplus", "softsign", "gelu", "silu",
    "sign",
]


def _make_unary(op_type):
    def layer(x, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_tmp_variable(dtype=dtype_name(x.dtype),
                                         shape=x.shape)
        helper.append_op(type=op_type, inputs={"X": [x]},
                         outputs={"Out": [out]})
        return out

    layer.__name__ = op_type
    layer.__doc__ = f"Elementwise {op_type} (≙ activation_op.cc kernel)."
    return layer


_mod = sys.modules[__name__]
for _op in _UNARY_OPS:
    setattr(_mod, _op, _make_unary(_op))

__all__ = list(_UNARY_OPS) + ["leaky_relu", "elu", "pow", "hard_sigmoid",
                              "swish", "prelu", "brelu", "soft_shrink",
                              "hard_shrink", "thresholded_relu", "maxout"]


def _attr_unary(op_type, **defaults):
    def layer(x, name=None, **kwargs):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_tmp_variable(dtype=dtype_name(x.dtype),
                                         shape=x.shape)
        attrs = dict(defaults)
        attrs.update(kwargs)
        helper.append_op(type=op_type, inputs={"X": [x]},
                         outputs={"Out": [out]}, attrs=attrs)
        return out

    layer.__name__ = op_type
    return layer


leaky_relu = _attr_unary("leaky_relu", alpha=0.02)
elu = _attr_unary("elu", alpha=1.0)
pow = _attr_unary("pow", factor=1.0)
hard_sigmoid = _attr_unary("hard_sigmoid", slope=0.2, offset=0.5)
swish = _attr_unary("swish", beta=1.0)
brelu = _attr_unary("brelu", t_min=0.0, t_max=24.0)
soft_shrink = _attr_unary("soft_shrink", **{"lambda": 0.5})
hard_shrink = _attr_unary("hard_shrink", threshold=0.5)
thresholded_relu = _attr_unary("thresholded_relu", threshold=1.0)


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", name=name)
    n, c, h, w = x.shape
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype),
                                     shape=[n, c // groups, h, w])
    helper.append_op(type="maxout", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"groups": groups})
    return out


def prelu(x, mode="all", param_attr=None, name=None):
    from ..initializer import ConstantInitializer
    helper = LayerHelper("prelu", name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [x.shape[1]]
    else:
        alpha_shape = list(x.shape[1:])
    alpha = helper.create_parameter(
        param_attr, shape=alpha_shape, dtype=dtype_name(x.dtype),
        default_initializer=ConstantInitializer(0.25))
    out = helper.create_tmp_variable(dtype=dtype_name(x.dtype), shape=x.shape)
    helper.append_op(type="prelu", inputs={"X": [x], "Alpha": [alpha]},
                     outputs={"Out": [out]}, attrs={"mode": mode})
    return out
