"""Program-rewrite layer (≙ reference python/paddle/fluid/transpiler/).

The reference rewrites ProgramDescs before execution:
- memory_optimization_transpiler.py:381  — liveness-based var reuse
- inference_transpiler.py:24             — fold BN into conv weights
- distribute_transpiler.py:131           — split program into trainer/pserver
- ps_dispatcher.py                       — shard→endpoint dispatch policies

TPU translation: XLA already does buffer reuse and fusion, so the memory
transpiler becomes (a) rematerialization policy on the autodiff region and
(b) live-out narrowing of published forward vars; the inference transpiler
is a real program+scope rewrite (constant folding BN into conv); the
distribute transpiler becomes a sharding *planner* over a device mesh rather
than an RPC program splitter (SURVEY.md §2.3), while keeping the reference's
API surface so programs written against it keep working.
"""

from .memory_optimization import memory_optimize, release_memory
from .inference_transpiler import InferenceTranspiler
from .quantize_transpiler import QuantizeTranspiler
from .distribute_transpiler import (DistributeTranspiler,
                                    DistributeTranspilerConfig, slice_variable)
from .ps_dispatcher import HashName, PSDispatcher, RoundRobin

__all__ = [
    "memory_optimize", "release_memory", "InferenceTranspiler",
    "QuantizeTranspiler", "DistributeTranspiler",
    "DistributeTranspilerConfig", "slice_variable",
    "PSDispatcher", "RoundRobin", "HashName",
]
