"""Inference transpiler: fold batch-norm into the preceding conv/fc.

≙ reference python/paddle/fluid/transpiler/inference_transpiler.py:24, which
rewrites an inference program so that `conv2d → batch_norm` (optionally with a
bias elementwise_add in between) becomes a single conv with adjusted weights:

    W' = W * (scale / sqrt(var + eps))          (per output channel)
    b' = (b - mean) * scale / sqrt(var + eps) + offset

The arithmetic is identical here; what differs is the mechanics — the rewrite
mutates the in-memory Program and the Scope holding parameter values (no
protobuf round-trip), and XLA recompiles the smaller program on next run.
"""

from __future__ import annotations

import numpy as np

from ..core.enforce import InvalidArgumentError, enforce
from ..framework.program import Program
from ..framework.scope import Scope, global_scope


def _as_np(x):
    return np.asarray(x)


class InferenceTranspiler:
    """≙ reference InferenceTranspiler (inference_transpiler.py:24)."""

    def transpile(self, program: Program, place=None, scope: Scope = None):
        """Fuse batch_norm into conv2d/depthwise_conv2d/mul producers,
        in place. `place` is accepted for API parity and ignored (XLA owns
        placement)."""
        enforce(isinstance(program, Program),
                InvalidArgumentError, "program must be a Program")
        scope = scope or global_scope()
        block = program.global_block()
        self._fuse_batch_norms(block, scope)
        program._bump()
        return program

    # -- internals ---------------------------------------------------------

    def _producer(self, block, name, upto):
        """Last op before index `upto` writing `name`."""
        for j in range(upto - 1, -1, -1):
            if name in block.ops[j].output_names():
                return j
        return None

    def _n_readers(self, block, name):
        return sum(name in op.input_names() for op in block.ops)

    def _fuse_batch_norms(self, block, scope):
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if op.type != "batch_norm" or not op.attrs.get("is_test"):
                i += 1
                continue
            x_name = op.inputs["X"][0]
            prod_idx = self._producer(block, x_name, i)
            if prod_idx is None:
                i += 1
                continue

            # walk back through a bias elementwise_add to the conv; a
            # residual add (Y not a stored 1-D bias) is not foldable
            add_idx = None
            conv_idx = prod_idx
            if block.ops[prod_idx].type == "elementwise_add":
                y_name = block.ops[prod_idx].inputs["Y"][0]
                y_val = scope.find_var(y_name)
                if y_val is None or _as_np(y_val).ndim != 1:
                    i += 1
                    continue
                add_idx = prod_idx
                conv_in = block.ops[add_idx].inputs["X"][0]
                conv_idx = self._producer(block, conv_in, add_idx)
                if conv_idx is None:
                    i += 1
                    continue
            conv = block.ops[conv_idx]
            if conv.type not in ("conv2d", "depthwise_conv2d", "mul"):
                i += 1
                continue
            # neither the BN input nor the conv output may feed anything
            # else — folding rescales the filter all consumers would see
            conv_out = conv.output_names()[0]
            if self._n_readers(block, x_name) != 1 or \
                    self._n_readers(block, conv_out) != 1:
                i += 1
                continue

            scale = _as_np(scope.get(op.inputs["Scale"][0]))
            offset = _as_np(scope.get(op.inputs["Bias"][0]))
            mean = _as_np(scope.get(op.inputs["Mean"][0]))
            var = _as_np(scope.get(op.inputs["Variance"][0]))
            eps = op.attrs.get("epsilon", 1e-5)
            factor = scale / np.sqrt(var + eps)  # [C_out]

            # fold into the producer's weights
            if conv.type == "mul":
                w_name = conv.inputs["Y"][0]
                w = _as_np(scope.get(w_name)).astype(np.float64)
                w = w * factor[None, :]
            else:
                w_name = conv.inputs["Filter"][0]
                w = _as_np(scope.get(w_name)).astype(np.float64)
                w = w * factor[:, None, None, None]   # OIHW: out-channel axis 0
            orig_dtype = _as_np(scope.get(w_name)).dtype
            scope.set_var(w_name, w.astype(orig_dtype))

            # fold into (possibly existing) bias
            if add_idx is not None:
                b_name = block.ops[add_idx].inputs["Y"][0]
                b = _as_np(scope.get(b_name)).astype(np.float64)
                b_new = (b - mean) * factor + offset
                scope.set_var(b_name, b_new.astype(orig_dtype))
                # batch_norm becomes identity: retarget the add's output name
                bn_out = op.outputs["Y"][0]
                block.ops[add_idx].outputs["Out"] = [bn_out]
                del block.ops[i]
            else:
                # no existing bias: turn the batch_norm op itself into the
                # bias add (keeps op count/positions stable)
                b_new = (offset - mean * factor).astype(orig_dtype)
                b_name = op.inputs["Bias"][0] + ".fused"
                if not block.has_var(b_name):
                    data_format = conv.attrs.get("data_format", "NCHW")
                    block.create_var(name=b_name, shape=list(b_new.shape),
                                     dtype=str(orig_dtype), persistable=True)
                scope.set_var(b_name, b_new)
                # axis of the channel dim in the BN input
                bn_layout = op.attrs.get("data_layout", "NCHW")
                x_var = block.vars.get(x_name)
                ndim = len(x_var.shape) if x_var is not None else 4
                axis = 1 if bn_layout == "NCHW" and ndim == 4 else ndim - 1
                bn_out = op.outputs["Y"][0]
                op.type = "elementwise_add"
                op.inputs = {"X": [x_name], "Y": [b_name]}
                op.outputs = {"Out": [bn_out]}
                op.attrs = {"axis": axis, "op_role": op.attrs.get("op_role")}
                i += 1
                continue
            # do not advance: current index now holds the next op
        return block
