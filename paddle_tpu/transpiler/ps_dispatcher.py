"""Shard → endpoint dispatch policies.

≙ reference python/paddle/fluid/transpiler/ps_dispatcher.py (RoundRobin /
HashName). Used by the DistributeTranspiler planner to assign parameter
shards to workers/hosts, and by the sharded-embedding path to place table
shards.
"""

from __future__ import annotations

from typing import List, Sequence


class PSDispatcher:
    """Base dispatcher over a list of endpoints (≙ reference PSDispatcher)."""

    def __init__(self, pserver_endpoints: Sequence[str]):
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self) -> List[str]:
        return list(self._eps)

    def reset(self):
        self._step = 0

    def dispatch(self, varlist) -> List[str]:
        raise NotImplementedError


class RoundRobin(PSDispatcher):
    """≙ reference RoundRobin: cycle endpoints in order."""

    def dispatch(self, varlist) -> List[str]:
        out = []
        for _ in varlist:
            out.append(self._eps[self._step % len(self._eps)])
            self._step += 1
        return out


class HashName(PSDispatcher):
    """≙ reference HashName: stable assignment by name hash — a var always
    lands on the same endpoint regardless of dispatch order."""

    @staticmethod
    def _hash(name: str) -> int:
        # deterministic across processes (unlike builtin hash w/ PYTHONHASHSEED)
        h = 2166136261
        for ch in name.encode():
            h = ((h ^ ch) * 16777619) & 0xFFFFFFFF
        return h

    def dispatch(self, varlist) -> List[str]:
        out = []
        for v in varlist:
            name = getattr(v, "name", None) or str(v)
            out.append(self._eps[self._hash(name) % len(self._eps)])
        return out
