"""Distribute transpiler: parameter-shard planner with the reference's API.

≙ reference python/paddle/fluid/transpiler/distribute_transpiler.py:131.
The reference splits each param/grad into ~even blocks (slice_variable :69),
dispatches shards to parameter-server endpoints, and rewrites the trainer
program with send/recv/barrier RPC ops; each pserver runs the optimizer for
its shards.

TPU-native translation (SURVEY.md §2.3): the *transport* (gRPC send/recv)
becomes XLA collectives compiled into the step, and the *sharded optimizer
state* becomes the ZeRO-style reduce-scatter path in ParallelExecutor. What
remains genuinely useful from the pserver design — and is implemented here —
is the planning layer:

- `slice_variable`: the reference's even-block splitting math, reused for
  balanced shard sizing (≙ distribute_transpiler.py:69, min_block_size 8192).
- `DistributeTranspiler.transpile`: assigns every (param, grad) shard to a
  worker via a PSDispatcher, annotates the trainer program with the shard
  plan (consumed by ParallelExecutor's kReduce/ZeRO path as the
  size-balanced ownership map ≙ GetAppropriateDeviceID,
  multi_devices_graph_pass.cc:261), and
- `get_pserver_program`: materializes a runnable per-endpoint Program holding
  that endpoint's param shards + their optimizer ops — the host-side
  parameter-service capability (giant embeddings that exceed device HBM),
  executable with a plain Executor by feeding gradient shards.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..core.dtypes import dtype_name
from ..core.enforce import InvalidArgumentError, enforce
from ..framework.program import Program, Variable, default_main_program
from .ps_dispatcher import PSDispatcher, RoundRobin

MIN_BLOCK_SIZE = 8192  # ≙ reference distribute_transpiler.py:128


@dataclass
class VarBlock:
    """One shard of a variable (≙ reference VarBlock "varname:blockid:size")."""
    varname: str
    block_id: int
    begin: int   # flat-element offset
    size: int    # flat-element count

    def __str__(self):
        return f"{self.varname}:{self.block_id}:{self.size}"


def slice_variable(var_list: Sequence[Variable], slice_count: int,
                   min_block_size: int = MIN_BLOCK_SIZE) -> List[List[VarBlock]]:
    """Split each var into at most `slice_count` ~even flat blocks of at
    least `min_block_size` elements (≙ reference slice_variable,
    distribute_transpiler.py:69). Returns one block list per input var."""
    blocks: List[List[VarBlock]] = []
    for var in var_list:
        numel = 1
        for d in var.shape:
            numel *= max(int(d), 1)
        split_count = min(slice_count,
                          max(1, numel // min_block_size))
        block_size = int(math.ceil(numel / float(split_count)))
        if numel > 1 and len(var.shape) >= 1 and var.shape[0] > 0:
            # align to whole rows like the reference, so a shard is a
            # contiguous row range (needed for embedding-row dispatch)
            dim1 = max(1, numel // max(int(var.shape[0]), 1))
            remains = block_size % dim1
            if remains != 0:
                block_size += dim1 - remains
        split_count = int(math.ceil(numel / float(block_size)))
        var_blocks = []
        for b in range(split_count):
            begin = b * block_size
            size = min(block_size, numel - begin)
            var_blocks.append(VarBlock(var.name, b, begin, size))
        blocks.append(var_blocks)
    return blocks


@dataclass
class ShardPlan:
    """Result of transpile(): who owns which shard."""
    # endpoint -> list of (param VarBlock, grad VarBlock, optimize op index)
    by_endpoint: Dict[str, List] = field(default_factory=dict)
    # varname -> list of (VarBlock, endpoint)
    by_var: Dict[str, List] = field(default_factory=dict)
    trainers: int = 1
    sync_mode: bool = True


@dataclass
class DistributeTranspilerConfig:
    """≙ reference DistributeTranspilerConfig: split_method is a
    PSDispatcher subclass; min_block_size bounds shard granularity."""
    split_method: type = RoundRobin
    min_block_size: int = MIN_BLOCK_SIZE
    slice_var_up: bool = True


class DistributeTranspiler:
    """≙ reference DistributeTranspiler (distribute_transpiler.py:131)."""

    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._plan: Optional[ShardPlan] = None
        self._program: Optional[Program] = None
        # pserver-program id -> {var name: non-zero init value}
        self._init_values: Dict[int, Dict[str, float]] = {}

    @staticmethod
    def _numel(var) -> int:
        n = 1
        for d in var.shape:
            n *= max(int(d), 1)
        return n

    def _acc_shape_and_init(self, src_block, src_name: str, pb: VarBlock,
                            src_op=None, slot: str = ""):
        """Shard shape + startup init for an optimizer accumulator. An
        accumulator with the PARAM's total numel shards to [pb.size];
        anything else (scalar beta-power state etc.) keeps its source shape.
        Beta-power init comes from the optimizer op's own attrs (the exact
        value the trainer would start from), falling back to the live scope
        value, so pserver math matches trainer math."""
        src_var = src_block.vars.get(src_name)
        param_var = src_block.vars.get(pb.varname)
        if src_var is not None and param_var is not None and \
                self._numel(src_var) != self._numel(param_var):
            init = None
            if src_op is not None:
                # adam/adamax beta-power accumulators start at beta^1
                if slot.startswith("Beta1Pow") and "beta1" in src_op.attrs:
                    init = float(src_op.attrs["beta1"])
                elif slot.startswith("Beta2Pow") and "beta2" in src_op.attrs:
                    init = float(src_op.attrs["beta2"])
            if init is None:
                try:
                    from ..framework.scope import global_scope
                    import numpy as _np
                    init = float(_np.asarray(
                        global_scope().get(src_name)).reshape(-1)[0])
                except Exception:
                    init = None
            return list(src_var.shape), init
        return [pb.size], None

    # -- the main entry (reference :179) ----------------------------------

    def transpile(self, trainer_id: int, program: Optional[Program] = None,
                  pservers: str = "127.0.0.1:6174", trainers: int = 1,
                  sync_mode: bool = True, startup_program=None):
        enforce(trainer_id >= 0, InvalidArgumentError,
                "trainer_id must be >= 0")
        program = program or default_main_program()
        eps = pservers.split(",") if isinstance(pservers, str) else list(pservers)
        dispatcher: PSDispatcher = self.config.split_method(eps)

        block = program.global_block()
        params = [p for p in program.all_parameters() if p.trainable]
        # optimize ops keyed by the param they update
        opt_ops: Dict[str, int] = {}
        for i, op in enumerate(block.ops):
            if op.attrs.get("op_role") == "optimize" and "Param" in op.inputs:
                opt_ops[op.inputs["Param"][0]] = i

        plan = ShardPlan(trainers=trainers, sync_mode=sync_mode)
        slice_count = len(eps) if self.config.slice_var_up else 1
        grouped = slice_variable(params, slice_count,
                                 self.config.min_block_size)
        for param, pblocks in zip(params, grouped):
            endpoints = dispatcher.dispatch(pblocks)
            for vb, ep in zip(pblocks, endpoints):
                gb = VarBlock(vb.varname + "@GRAD", vb.block_id,
                              vb.begin, vb.size)
                plan.by_endpoint.setdefault(ep, []).append(
                    (vb, gb, opt_ops.get(param.name)))
                plan.by_var.setdefault(param.name, []).append((vb, ep))

        # Annotate the trainer program: ParallelExecutor's reduce/ZeRO path
        # reads this as the shard-ownership map (the TPU translation of the
        # send/recv rewrite — collectives are compiled in, not appended).
        for param in params:
            owners = [ep for _, ep in plan.by_var[param.name]]
            for op in block.ops:
                if op.attrs.get("op_role") == "optimize" and \
                        op.inputs.get("Param", [None])[0] == param.name:
                    op.attrs["shard_endpoints"] = owners
        program._bump()
        self._plan = plan
        self._program = program
        return self

    # -- program accessors (reference get_trainer_program :343 /
    #    get_pserver_program :397) ----------------------------------------

    def get_trainer_program(self) -> Program:
        """The trainer-side program. Unlike the reference (which inserts
        send/recv ops), gradients flow through compiled collectives; the
        program is returned with shard annotations only."""
        enforce(self._program is not None, InvalidArgumentError,
                "call transpile() first")
        return self._program

    def get_shard_plan(self) -> ShardPlan:
        enforce(self._plan is not None, InvalidArgumentError,
                "call transpile() first")
        return self._plan

    def get_pserver_program(self, endpoint: str) -> Program:
        """A runnable host-side parameter-service program for `endpoint`:
        for each assigned shard, a param-shard var, a grad-shard feed var,
        and the optimizer op cloned onto the shard. ≙ reference
        get_pserver_program (one optimize sub-block per shard,
        distribute_transpiler.py:397 / listen_and_serv_op.cc:102)."""
        enforce(self._plan is not None, InvalidArgumentError,
                "call transpile() first")
        shards = self._plan.by_endpoint.get(endpoint, [])
        src_block = self._program.global_block()
        prog = Program()
        blk = prog.global_block()
        for pb, gb, opt_idx in shards:
            suffix = f".block{pb.block_id}"
            pname, gname = pb.varname + suffix, gb.varname + suffix
            blk.create_var(name=pname, shape=[pb.size], dtype="float32",
                           persistable=True)
            blk.create_var(name=gname, shape=[gb.size], dtype="float32")
            if opt_idx is None:
                # no optimizer on this param — plain sgd placeholder is NOT
                # appended; shard is fetch/update-by-assignment only
                continue
            src_op = src_block.ops[opt_idx]
            inputs = {"Param": [pname], "Grad": [gname]}
            outputs = {"ParamOut": [pname]}
            for slot, names in src_op.inputs.items():
                if slot in ("Param", "Grad"):
                    continue
                if slot == "LearningRate":
                    lr = names[0]
                    if not blk.has_var(lr):
                        blk.create_var(name=lr, shape=[], dtype="float32",
                                       persistable=True)
                    inputs[slot] = [lr]
                else:
                    # accumulator shard: param-shaped accumulators (moments)
                    # shard with the param; scalar state (Adam's Beta1Pow/
                    # Beta2Pow) keeps its own shape and initial value
                    acc = names[0] + suffix
                    if not blk.has_var(acc):
                        shape, init = self._acc_shape_and_init(
                            src_block, names[0], pb, src_op, slot)
                        blk.create_var(name=acc, shape=shape,
                                       dtype="float32", persistable=True)
                        if init is not None:
                            self._init_values.setdefault(id(prog), {})[
                                acc] = init
                    inputs[slot] = [acc]
            for slot, names in src_op.outputs.items():
                if slot in ("ParamOut",):
                    continue
                outputs[slot] = [names[0] + suffix]
                tgt = names[0] + suffix
                if not blk.has_var(tgt):
                    shape, init = self._acc_shape_and_init(
                        src_block, names[0], pb, src_op, slot)
                    blk.create_var(name=tgt, shape=shape,
                                   dtype="float32", persistable=True)
                    if init is not None:
                        self._init_values.setdefault(id(prog), {})[
                            tgt] = init
            blk.append_op(type=src_op.type, inputs=inputs, outputs=outputs,
                          attrs={k: v for k, v in src_op.attrs.items()
                                 if k not in ("shard_endpoints",)})
        return prog

    def get_startup_program(self, endpoint: str,
                            pserver_program: Optional[Program] = None):
        """Startup program initializing `endpoint`'s shard vars to zeros
        (real values arrive via the first checkpoint/push, as in the
        reference where trainers push initial params)."""
        prog = pserver_program or self.get_pserver_program(endpoint)
        inits = self._init_values.get(id(prog), {})
        startup = Program()
        blk = startup.global_block()
        for name, var in prog.global_block().vars.items():
            if not var.persistable:
                continue
            blk.create_var(name=name, shape=var.shape, dtype=var.dtype,
                           persistable=True)
            blk.append_op(type="fill_constant", inputs={},
                          outputs={"Out": [name]},
                          attrs={"shape": list(var.shape) or [],
                                 "dtype": dtype_name(var.dtype),
                                 "value": inits.get(name, 0.0)})
        return startup
