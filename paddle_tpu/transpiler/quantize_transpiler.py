"""Quantization-aware-training transpiler.

≙ reference fake_quantize_op.cc / fake_dequantize_op.cc (SURVEY.md §2.2
"Quantization") plus the program-rewrite pattern of the reference's
transpilers: insert fake-quant (quantize→dequantize with a straight-through
estimator) on the activation and weight inputs of matmul-bearing ops so
training observes int8 rounding while gradients flow.

On TPU the quantized *execution* path is XLA int8 matmul; this transpiler
provides the QAT graph rewrite and a `freeze_program` step that bakes weight
scales in, mirroring the reference's train→freeze flow.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..framework.program import Program
from ..framework.scope import Scope, global_scope

_QUANTIZABLE = ("conv2d", "depthwise_conv2d", "mul", "matmul")
# slot holding the weight operand per op type
_WEIGHT_SLOT = {"conv2d": "Filter", "depthwise_conv2d": "Filter",
                "mul": "Y", "matmul": "Y"}
_ACT_SLOT = {"conv2d": "Input", "depthwise_conv2d": "Input",
             "mul": "X", "matmul": "X"}


class QuantizeTranspiler:
    """Insert fake-quant ops for QAT; freeze for inference.

    ≙ the reference's fake_quantize/fake_dequantize op pair wired by a
    program rewrite (quantization hooks, SURVEY.md §7 stage 10).
    """

    def __init__(self, weight_bits: int = 8, activation_bits: int = 8,
                 activation_quantize_type: str = "abs_max",
                 weight_quantize_type: str = "abs_max",
                 moving_rate: float = 0.9):
        if activation_quantize_type not in ("abs_max",
                                            "moving_average_abs_max"):
            raise ValueError(
                f"unsupported activation_quantize_type "
                f"{activation_quantize_type!r}")
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.activation_quantize_type = activation_quantize_type
        self.weight_quantize_type = weight_quantize_type
        self.moving_rate = moving_rate

    # -- QAT rewrite -------------------------------------------------------

    def training_transpile(self, program: Optional[Program] = None,
                           startup_program: Optional[Program] = None):
        """Rewrite `program` in place: every quantizable op's activation and
        weight inputs go through a fake_quantize op first."""
        from ..framework.program import default_main_program
        program = program or default_main_program()
        if any(op.type == "vjp_region"
               for b in program.blocks for op in b.ops):
            raise RuntimeError(
                "training_transpile must run BEFORE optimizer.minimize()/"
                "append_backward — inserting quant ops after autodiff would "
                "invalidate the recorded forward segment")
        return self._rewrite_clean(program, startup_program)

    def _rewrite_clean(self, program: Program,
                       startup_program: Optional[Program] = None) -> Program:
        from ..framework.program import Operator
        block = program.global_block()
        new_ops = []
        quantized: dict = {}
        for op in block.ops:
            if op.type in _QUANTIZABLE and not op.attrs.get("skip_quant") \
                    and not op.attrs.get("quantized"):
                for slot, bits, kind in (
                        (_ACT_SLOT[op.type], self.activation_bits,
                         self.activation_quantize_type),
                        (_WEIGHT_SLOT[op.type], self.weight_bits,
                         self.weight_quantize_type)):
                    name = op.inputs[slot][0]
                    key = (name, bits, kind)
                    if key not in quantized:
                        src = block.vars.get(name)
                        qname = name + ".quantized"
                        sname = name + ".quant_scale"
                        if not block.has_var(qname):
                            block.create_var(
                                name=qname,
                                shape=None if src is None else src.shape,
                                dtype="float32" if src is None else src.dtype)
                        if not block.has_var(sname):
                            block.create_var(name=sname, shape=[],
                                             dtype="float32",
                                             stop_gradient=True)
                        qtype = ("fake_quantize_abs_max"
                                 if kind == "abs_max" else
                                 "fake_quantize_moving_average_abs_max")
                        if qtype == "fake_quantize_moving_average_abs_max":
                            # the scale var doubles as the moving-average
                            # state: same persistable var in and out, so the
                            # executor's state write-back advances it (same
                            # pattern as batch-norm moving stats)
                            block.vars[sname].persistable = True
                            qop = Operator(
                                block, qtype,
                                inputs={"X": [name], "InScale": [sname]},
                                outputs={"Out": [qname], "OutScale": [sname]},
                                attrs={"bit_length": bits,
                                       "moving_rate": self.moving_rate,
                                       "op_role": op.attrs.get("op_role")})
                            from ..framework.program import \
                                default_startup_program
                            sp = startup_program or default_startup_program()
                            spb = sp.global_block()
                            if not spb.has_var(sname):
                                spb.create_var(name=sname, shape=[],
                                               dtype="float32",
                                               persistable=True)
                                spb.append_op(
                                    type="fill_constant", inputs={},
                                    outputs={"Out": [sname]},
                                    attrs={"shape": [], "dtype": "float32",
                                           "value": 0.0})
                        else:
                            qop = Operator(
                                block, qtype,
                                inputs={"X": [name]},
                                outputs={"Out": [qname],
                                         "OutScale": [sname]},
                                attrs={"bit_length": bits,
                                       "op_role": op.attrs.get("op_role")})
                        new_ops.append(qop)
                        quantized[key] = qname
                    op.inputs[slot] = [quantized[key]]
                op.attrs["quantized"] = True
            new_ops.append(op)
        block.ops[:] = new_ops
        program._bump()
        return program

    # -- freeze ------------------------------------------------------------

    def freeze_program(self, program: Program, place=None,
                       scope: Scope = None) -> Program:
        """Bake weight quantization into stored weights for inference
        (≙ the reference freeze flow: weights become their rounded values,
        activation fake-quant stays as calibrated scale ops)."""
        scope = scope or global_scope()
        block = program.global_block()
        bnt = (1 << (self.weight_bits - 1)) - 1
        for op in block.ops:
            if op.type != "fake_quantize_abs_max":
                continue
            name = op.inputs["X"][0]
            if not scope.has_var(name):
                continue  # activation, not a stored weight
            w = np.asarray(scope.get(name)).astype(np.float64)
            s = np.abs(w).max()
            inv = bnt / max(s, 1e-12)
            scope.set_var(name, (np.round(w * inv) / inv).astype(np.float32))
            scope.set_var(op.outputs["OutScale"][0],
                          np.asarray(s, dtype=np.float32))
        program._bump()
        return program
