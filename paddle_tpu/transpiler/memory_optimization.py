"""Memory-optimization transpiler.

≙ reference python/paddle/fluid/transpiler/memory_optimization_transpiler.py
(ControlFlowGraph :47, memory_optimize :381, release_memory :400). The
reference reuses variable buffers based on liveness over the interpreted
program. On TPU, XLA's buffer assignment already reuses dead buffers inside
the compiled step, so the two levers that remain meaningful are:

1. **Rematerialization** — the dominant memory knob on TPU: recompute forward
   activations during the backward pass instead of saving them
   (jax.checkpoint on the vjp region). `level` selects the policy.
2. **Live-out narrowing** — a real liveness pass over the program (the
   ControlFlowGraph analogue) that computes which forward vars are read
   *after* the autodiff region (metrics, fetches, optimizer inputs) and
   restricts the region's published outputs to that set, shrinking the
   compiled step's result buffers.
"""

from __future__ import annotations

from typing import Optional, Sequence, Set

from ..framework.program import Program

# level → jax.checkpoint policy name (None = save nothing, full remat)
_LEVELS = {
    0: "dots_with_no_batch_dims_saveable",  # save matmul outputs (cheap)
    1: None,                                # full remat: max memory savings
}


def _liveness_after_region(block, region_idx: int, seg: Sequence[int],
                           fetch_names: Set[str]) -> Set[str]:
    """Names read by any op after the region (skipping the region's own
    consumed forward ops) plus fetch targets — the region's live-out set
    (≙ ControlFlowGraph liveness, memory_optimization_transpiler.py:47)."""
    consumed = set(seg)
    live: Set[str] = set(fetch_names)
    for j, op in enumerate(block.ops):
        if j == region_idx or j in consumed:
            continue
        if j > min(seg):  # anything at/after the region's execution point
            live |= set(op.input_names())
    # persistable vars written inside the region (batch-norm moving stats,
    # moving quant scales) must survive: the executor writes them back to
    # the scope even though no later op reads them
    for j in seg:
        for name in block.ops[j].output_names():
            var = block.vars.get(name)
            if var is not None and getattr(var, "persistable", False):
                live.add(name)
    return live


def memory_optimize(input_program: Program,
                    skip_opt_set: Optional[Sequence[str]] = None,
                    print_log: bool = False,
                    level: int = 0) -> Program:
    """Rewrite `input_program` in place to reduce peak device memory.

    ≙ reference memory_optimize (memory_optimization_transpiler.py:381).
    level 0: remat everything except matmul/conv outputs (good default —
             recomputing elementwise chains is nearly free on TPU, while
             MXU results are expensive to recompute).
    level 1: full rematerialization (maximum memory savings).
    skip_opt_set: var names that must stay available after the step even if
             liveness says otherwise (≙ reference skip_opt_set).
    """
    if level not in _LEVELS:
        raise ValueError(f"memory_optimize level must be one of "
                         f"{sorted(_LEVELS)}, got {level!r}")
    skip = set(skip_opt_set or ())
    for block in input_program.blocks:
        for i, op in enumerate(block.ops):
            if op.type != "vjp_region":
                continue
            op.attrs["remat"] = True
            policy = _LEVELS[level]
            if policy is not None:
                op.attrs["remat_policy"] = policy
            else:
                op.attrs.pop("remat_policy", None)
            seg = op.attrs.get("fwd_ops") or []
            if seg:
                live = _liveness_after_region(
                    block, i, seg, fetch_names=skip)
                # loss + anything liveness found + explicit keeps
                live.add(op.attrs["loss"])
                op.attrs["live_out"] = sorted(live)
            if print_log:
                kept = len(op.attrs.get("live_out", []))
                print(f"memory_optimize: region@{i} remat="
                      f"{_LEVELS[level] or 'full'} live_out={kept} vars")
    input_program._bump()
    return input_program


def release_memory(input_program: Program,
                   skip_opt_set: Optional[Sequence[str]] = None) -> Program:
    """Narrow region live-outs without enabling remat.

    ≙ reference release_memory (memory_optimization_transpiler.py:400), which
    inserts delete_var ops for dead vars. Here dead forward vars are simply
    not published from the autodiff region; XLA then frees (or never
    materializes) them.
    """
    skip = set(skip_opt_set or ())
    for block in input_program.blocks:
        for i, op in enumerate(block.ops):
            if op.type != "vjp_region":
                continue
            seg = op.attrs.get("fwd_ops") or []
            if seg:
                live = _liveness_after_region(block, i, seg, fetch_names=skip)
                live.add(op.attrs["loss"])
                op.attrs["live_out"] = sorted(live)
    input_program._bump()
    return input_program
