"""Server-mode predictor: long-lived serve loop with concurrent requests.

≙ reference inference/api/api_impl.cc:126 (NativePaddlePredictor::Run — a
long-lived predictor object fielding many requests) and :170 (::Clone — one
shared-weights predictor per serving thread). The TPU translation:

- PredictorServer accepts TCP connections; each connection is served by a
  thread holding its own `predictor.clone()` (shared weights/executable
  cache source, private executor caches) — the clone-per-thread contract.
- The wire protocol is length-prefixed JSON + raw little-endian C-order
  tensor bytes, so clients in any language can speak it.
- A connection may pipeline requests (send several before reading): the
  per-connection thread answers strictly in order while OTHER connections
  run concurrently — XLA executions release the GIL, so concurrent
  requests genuinely overlap on device.

Protocol, per request:
    u32  header length
    JSON {"feeds": [{"name", "dtype", "shape"}...], "fetch": [...]? }
    raw tensor bytes for each feed, in header order
Response:
    u32  header length
    JSON {"outs": [{"name", "dtype", "shape"}...]}   (or {"error": msg})
    raw tensor bytes for each out
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


def _send_msg(sock: socket.socket, header: dict, buffers=()):
    raw = json.dumps(header).encode()
    sock.sendall(struct.pack("<I", len(raw)))
    sock.sendall(raw)
    for b in buffers:
        sock.sendall(b)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        c = sock.recv(min(n, 1 << 20))
        if not c:
            raise ConnectionError("peer closed")
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)


def _recv_msg(sock: socket.socket):
    try:
        hlen, = struct.unpack("<I", _recv_exact(sock, 4))
    except ConnectionError:
        return None, None
    header = json.loads(_recv_exact(sock, hlen))
    buffers = []
    for spec in header.get("feeds", header.get("outs", [])):
        n = int(np.prod(spec["shape"])) * np.dtype(spec["dtype"]).itemsize
        buffers.append(_recv_exact(sock, n))
    return header, buffers


class PredictorServer:
    """Serve a Predictor (or ExportedPredictor) over TCP.

    `predictor` needs .run(feed, fetch_names=None, return_numpy=True); if it
    has .clone(), every connection thread gets its own clone (≙ reference
    api_impl.cc:170), otherwise the single object is shared (safe for
    ExportedPredictor, whose call is stateless).
    """

    def __init__(self, predictor, host: str = "127.0.0.1", port: int = 0):
        self._base = predictor
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.address = self._sock.getsockname()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._lock = threading.Lock()
        self._accept_thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "PredictorServer":
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        return self

    def shutdown(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        # close live connections so threads blocked in recv() exit NOW
        # instead of eating the join timeout each
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.shutdown()

    # -- internals --------------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed by shutdown
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            with self._lock:
                self._conns.append(conn)
                self._threads = [x for x in self._threads if x.is_alive()]
                self._threads.append(t)
            t.start()

    def _serve_conn(self, conn: socket.socket):
        """Reader thread + worker thread per connection. The reader ALWAYS
        drains incoming requests into a queue and the worker executes +
        responds in order: with both roles on one thread, a client that
        pipelines faster than it reads would fill both TCP buffers and
        deadlock the pair in sendall (server not reading because it is
        writing). The queue is the explicit in-flight buffer instead."""
        import queue as _q

        # per-thread context reuse: ONE clone for the connection's lifetime,
        # its executor caches warm across requests
        predictor = (self._base.clone() if hasattr(self._base, "clone")
                     else self._base)
        # bounded: past 128 queued requests the reader stops reading and
        # TCP backpressure reaches the client — a runaway pipeliner stalls
        # itself instead of growing server memory without limit
        requests: "_q.Queue" = _q.Queue(maxsize=128)
        _EOF = object()
        # set when the worker exits for ANY reason: a reader blocked in
        # put() on a full queue must not wait forever for a consumer that
        # is gone (the worker also drains the queue on exit)
        worker_dead = threading.Event()

        def work():
            try:
                while True:
                    item = requests.get()
                    if item is _EOF:
                        return
                    header, buffers = item
                    try:
                        feed = {}
                        for spec, raw in zip(header["feeds"], buffers):
                            feed[spec["name"]] = np.frombuffer(
                                raw, dtype=np.dtype(spec["dtype"])).reshape(
                                    spec["shape"])
                        outs = predictor.run(
                            feed, fetch_names=header.get("fetch"),
                            return_numpy=True)
                        names = header.get("fetch") or getattr(
                            predictor, "fetch_names",
                            [f"out{i}" for i in range(len(outs))])
                        outs = [np.ascontiguousarray(o) for o in outs]
                        resp = {"outs": [
                            {"name": n, "dtype": str(o.dtype),
                             "shape": list(o.shape)}
                            for n, o in zip(names, outs)]}
                        _send_msg(conn, resp, [o.tobytes() for o in outs])
                    except Exception as e:  # per-request error, keep going
                        try:
                            _send_msg(conn,
                                      {"error": f"{type(e).__name__}: {e}"})
                        except OSError:
                            return
            except (ConnectionError, OSError):
                pass

        def work_outer():
            try:
                work()
            finally:
                worker_dead.set()
                try:  # unblock a reader stuck in put() on a full queue
                    while True:
                        requests.get_nowait()
                except _q.Empty:
                    pass

        def put_alive(item) -> bool:
            """put() that gives up once the worker is gone."""
            while not worker_dead.is_set():
                try:
                    requests.put(item, timeout=0.2)
                    return True
                except _q.Full:
                    continue
            return False

        worker = threading.Thread(target=work_outer, daemon=True)
        worker.start()
        try:
            while not self._stop.is_set():
                header, buffers = _recv_msg(conn)
                if header is None:
                    break
                if not put_alive((header, buffers)):
                    break
        except (ConnectionError, OSError):
            pass
        finally:
            put_alive(_EOF)
            worker.join(timeout=30)
            conn.close()
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)


class PredictorClient:
    """Client for PredictorServer; supports request pipelining.

    infer(feed) is the blocking RPC. For pipelined throughput, call
    send(feed) repeatedly and then recv() for each — responses arrive in
    order on one connection, so K in-flight requests hide the round trip.
    """

    def __init__(self, host: str, port: int):
        self._sock = socket.create_connection((host, port))
        self._lock = threading.Lock()  # serializes concurrent send()s

    def send(self, feed: Dict[str, Any],
             fetch: Optional[Sequence[str]] = None):
        arrays = {n: np.ascontiguousarray(v) for n, v in feed.items()}
        header = {"feeds": [{"name": n, "dtype": str(a.dtype),
                             "shape": list(a.shape)}
                            for n, a in arrays.items()]}
        if fetch is not None:
            header["fetch"] = list(fetch)
        with self._lock:
            _send_msg(self._sock, header,
                      [a.tobytes() for a in arrays.values()])

    def recv(self) -> List[np.ndarray]:
        header, buffers = _recv_msg(self._sock)
        if header is None:
            raise ConnectionError("server closed the connection")
        if "error" in header:
            raise RuntimeError(f"server error: {header['error']}")
        return [np.frombuffer(raw, dtype=np.dtype(spec["dtype"]))
                .reshape(spec["shape"])
                for spec, raw in zip(header["outs"], buffers)]

    def infer(self, feed: Dict[str, Any],
              fetch: Optional[Sequence[str]] = None) -> List[np.ndarray]:
        self.send(feed, fetch)
        return self.recv()

    def close(self):
        self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
