"""Parameter initializers.

≙ reference python/paddle/fluid/initializer.py — each initializer appends an
op to the *startup program* that fills the parameter; running the startup
program once initializes the scope (same two-program design as the reference,
framework.py:1958-2026).
"""

from __future__ import annotations

import numpy as np

from .core.dtypes import dtype_name


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, var, block):
        block.append_op("fill_constant", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "value": self.value,
                               "dtype": dtype_name(var.dtype)})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op("uniform_random", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "min": self.low,
                               "max": self.high, "seed": self.seed,
                               "dtype": dtype_name(var.dtype)})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op("gaussian_random", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "mean": self.loc,
                               "std": self.scale, "seed": self.seed,
                               "dtype": dtype_name(var.dtype)})


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op("truncated_gaussian_random",
                        outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "mean": self.loc,
                               "std": self.scale, "seed": self.seed,
                               "dtype": dtype_name(var.dtype)})


def _fan_in_out(var):
    shape = var.shape
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv filters OIHW: receptive field * channels
    rf = int(np.prod(shape[2:]))
    return shape[1] * rf, shape[0] * rf


class XavierInitializer(Initializer):
    """≙ fluid.initializer.Xavier (Glorot)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = \
            uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fin, fout = _fan_in_out(var)
        fin = self.fan_in if self.fan_in is not None else fin
        fout = self.fan_out if self.fan_out is not None else fout
        if self.uniform:
            limit = float(np.sqrt(6.0 / (fin + fout)))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = float(np.sqrt(2.0 / (fin + fout)))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    """≙ fluid.initializer.MSRA (He)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fin, _ = _fan_in_out(var)
        fin = self.fan_in if self.fan_in is not None else fin
        if self.uniform:
            limit = float(np.sqrt(6.0 / fin))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = float(np.sqrt(2.0 / fin))
            NormalInitializer(0.0, std, self.seed)(var, block)


class BilinearInitializer(Initializer):
    """≙ fluid.initializer.Bilinear — upsampling deconv filter init."""

    def __call__(self, var, block):
        shape = var.shape
        f = np.ceil(shape[-1] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype=np.float32)
        for idx in np.ndindex(*shape):
            x, y = idx[-1], idx[-2]
            weight[idx] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        block.append_op("assign_value", outputs={"Out": [var.name]},
                        attrs={"shape": list(shape),
                               "dtype": dtype_name(var.dtype),
                               "values": weight.reshape(-1).tolist()})


class NumpyArrayInitializer(Initializer):
    def __init__(self, value: np.ndarray):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        block.append_op("assign_value", outputs={"Out": [var.name]},
                        attrs={"shape": list(self.value.shape),
                               "dtype": dtype_name(var.dtype),
                               "values": self.value.reshape(-1).tolist()})


# fluid-style aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


def _global_weight_initializer():
    return XavierInitializer()


def _global_bias_initializer():
    return ConstantInitializer(0.0)
