"""Fused decode-attention step: one KV-cache tick's QK^T·softmax·V in one
kernel.

≙ reference attention_lstm_fuse_pass.cc's fused attention step — the
reference fuses the decoder's per-step attention chain into one op; here
the chain is the cached-decode hot path (`models/transformer.py
_attend_cached`): matmul(q, K^T, alpha=scale) → +bias → softmax →
matmul(·, V), four kernels per tick per layer with the [.., 1, T]
score/weight tensors round-tripping HBM between them. The fused kernel
reads the cache ONCE and keeps scores/weights in VMEM. The cache WRITE
side stays on the existing `cache_write` dynamic-update-slice op — this
kernel only fuses the read side.

The query has exactly one position (the decode tick), so the score matrix
is [heads, T]: heads ride the sublane axis, cache positions the lane axis,
and the whole per-(batch·beam) computation is VPU element-wise + lane
reductions — decode attention is memory-bound, so the win is the single
pass over the cache, not MXU utilization.

Gradients (decode graphs are inference-only, but the op is registered
without `stop_gradient` for completeness): `jax.custom_vjp` whose backward
differentiates the identical XLA composite — exact.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..framework.registry import register_op

_NEG_INF = -1e30


def _auto_backend():
    from ..ops.pallas_kernels import _auto_backend as _ab
    return _ab()


def _round_up(n, m):
    return -(-n // m) * m


def _decode_xla(q4, k4, v4, bias4, scale):
    """Normalized-shape composite: q4 [R, nh, G, dh], k4/v4 [R, nh, T, dh],
    bias4 [R, nh, G, T]. G is 1 for the plain decode tick and γ+1 for a
    speculative verify forward. Replicates the unfused op chain's math
    exactly (matmul in f32 preferred type, alpha after, softmax last-axis).
    """
    s = jnp.matmul(q4, jnp.swapaxes(k4, -1, -2),
                   preferred_element_type=jnp.float32).astype(q4.dtype)
    s = s * scale + bias4
    w = jax.nn.softmax(s, axis=-1)
    out = jnp.matmul(w, v4, preferred_element_type=jnp.float32)
    return out.astype(q4.dtype)


def _decode_step_kernel(q_ref, k_ref, v_ref, b_ref, o_ref, *, scale):
    q = q_ref[0].astype(jnp.float32)                 # [nh, 1, dh] -> [nh, dh]
    q = q[:, 0, :]
    k = k_ref[0].astype(jnp.float32)                 # [nh, T, dh]
    v = v_ref[0].astype(jnp.float32)
    bias = b_ref[0]                                  # [nh, T]
    s = jnp.sum(q[:, None, :] * k, axis=-1) * scale + bias       # [nh, T]
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    w = p / l
    o_ref[0] = jnp.sum(w[:, :, None] * v, axis=1)[:, None, :].astype(
        o_ref.dtype)


def _decode_pallas(q4, k4, v4, bias3, scale, interpret):
    from jax.experimental import pallas as pl

    r, nh, _, dh = q4.shape
    t = k4.shape[2]
    nhp = _round_up(nh, 8)
    tp = _round_up(t, 128)

    def pad(a, axis, target, value=0.0):
        if a.shape[axis] == target:
            return a
        spec = [(0, 0)] * a.ndim
        spec[axis] = (0, target - a.shape[axis])
        return jnp.pad(a, spec, constant_values=value)

    qf = pad(q4, 1, nhp)
    kf = pad(pad(k4, 1, nhp), 2, tp)
    vf = pad(pad(v4, 1, nhp), 2, tp)
    # padded cache columns must be dead under softmax
    bf = pad(pad(bias3, 1, nhp), 2, tp, value=_NEG_INF)

    out = pl.pallas_call(
        functools.partial(_decode_step_kernel, scale=scale),
        grid=(r,),
        in_specs=[
            pl.BlockSpec((1, nhp, 1, dh), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, nhp, tp, dh), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, nhp, tp, dh), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, nhp, tp), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, nhp, 1, dh), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((r, nhp, 1, dh), q4.dtype),
        interpret=interpret,
    )(qf, kf, vf, bf)
    return out[:, :nh]


# per-grid-step VMEM budget for the kernel's K/V/bias blocks: stay well
# under the ~16 MB/core VMEM so the compiler has room for double buffering
_VMEM_BUDGET_BYTES = 6 << 20


def _pallas_fits(nh, t, dh):
    """Mosaic-path gate: the K/V/bias blocks must fit the VMEM budget and
    dh (the lane axis of every block) must be sublane-packable — dh % 8,
    matching the flash kernels' proven D=64 tiling. Anything else takes
    the identical XLA composite (same policy as recurrent._pallas_ok)."""
    nhp = _round_up(nh, 8)
    tp = _round_up(t, 128)
    return (dh % 8 == 0
            and nhp * tp * (2 * dh + 1) * 4 <= _VMEM_BUDGET_BYTES)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _decode_attention(q4, k4, v4, bias4, scale, backend):
    if backend != "xla" and (
            q4.shape[2] != 1   # Mosaic kernel is single-position only —
                               # verify widening (G>1) takes the composite
            or not _pallas_fits(q4.shape[1], k4.shape[2], q4.shape[3])):
        backend = "xla"   # cache block would blow the VMEM budget
    if backend == "xla":
        return _decode_xla(q4, k4, v4, bias4, scale)
    return _decode_pallas(q4, k4, v4, bias4[:, :, 0, :], scale,
                          interpret=(backend == "pallas_interpret"))


def _decode_attention_fwd(q4, k4, v4, bias4, scale, backend):
    return (_decode_attention(q4, k4, v4, bias4, scale, backend),
            (q4, k4, v4, bias4))


def _decode_attention_bwd(scale, backend, res, g):
    q4, k4, v4, bias4 = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_, b_: _decode_xla(q_, k_, v_, b_, scale),
        q4, k4, v4, bias4)
    return vjp(g)


_decode_attention.defvjp(_decode_attention_fwd, _decode_attention_bwd)


QUANT_KV_BLOCK_T = 8   # time-axis tile: one f32 scale per <=8 cache steps


def _fit_time_block(t, block):
    b = min(block, t)
    while t % b:
        b -= 1
    return b


def quantize_kv_time_blocks(kv, block=QUANT_KV_BLOCK_T):
    """Symmetric int8 quantization of a KV cache along the time axis.

    kv [..., T, dh] → (payload int8 [..., T, dh], scales f32 [..., T//bt])
    where bt is the largest divisor of T that is <= block, so the payload
    keeps the exact cache shape (no padding bytes). One scale covers a
    [bt, dh] tile per leading index — the time-local amax tracks the
    cache's per-step magnitude drift, which is what makes int8 caches
    viable for decode attention (same rationale as the gradient path's
    `quantize_blocks`, specialised to the cache layout)."""
    t, dh = kv.shape[-2], kv.shape[-1]
    bt = _fit_time_block(t, block)
    lead = kv.shape[:-2]
    tiles = jnp.asarray(kv, jnp.float32).reshape(lead + (t // bt, bt, dh))
    amax = jnp.max(jnp.abs(tiles), axis=(-1, -2), keepdims=True)
    sc = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(tiles / sc), -127, 127).astype(jnp.int8)
    return q.reshape(kv.shape), sc.reshape(lead + (t // bt,))


def dequantize_kv_time_blocks(q, scales, dtype=jnp.float32):
    """Inverse of `quantize_kv_time_blocks`: payload int8 [..., T, dh] +
    scales [..., T//bt] → dequantized [..., T, dh] in `dtype`."""
    t, dh = q.shape[-2], q.shape[-1]
    nb = scales.shape[-1]
    bt = t // nb
    lead = q.shape[:-2]
    tiles = q.astype(jnp.float32).reshape(lead + (nb, bt, dh))
    out = tiles * scales[..., :, None, None]
    return out.reshape(q.shape).astype(dtype)


def fused_decode_attention(q, k, v, bias, scale=1.0, backend=None,
                           k_scale=None, v_scale=None):
    """One decode tick of cached attention in one kernel.

    q [..., nh, G, dh] (G query positions: 1 for the plain decode tick,
    γ+1 for a speculative verify forward), k/v [..., nh, T, dh] (the KV
    cache), bias broadcastable to [..., nh, G, T] (additive mask hiding
    cache positions beyond each query's tick — causal within the verify
    window). Returns [..., nh, G, dh]. Equals matmul(q, k^T)*scale + bias
    → softmax → matmul(·, v) exactly. G == 1 may take the Pallas kernel;
    G > 1 always lowers through the identical XLA composite.

    Quantized variant: pass int8 k/v payloads plus `k_scale`/`v_scale`
    from `quantize_kv_time_blocks` (f32 [..., nh, T//bt]); the caches are
    dequantized per time block inside the lowering before the math —
    XLA fuses the rescale into the single cache read, so the HBM traffic
    is the int8 payload, not the f32 cache.
    """
    backend = backend or _auto_backend()
    if k_scale is not None:
        k = dequantize_kv_time_blocks(k, k_scale, dtype=q.dtype)
    if v_scale is not None:
        v = dequantize_kv_time_blocks(v, v_scale, dtype=q.dtype)
    lead = q.shape[:-3]
    nh, g, dh = q.shape[-3], q.shape[-2], q.shape[-1]
    t = k.shape[-2]
    r = 1
    for d in lead:
        r *= d
    q4 = q.reshape((r, nh, g, dh))
    k4 = jnp.broadcast_to(k, lead + k.shape[-3:]).reshape((r, nh, t, dh))
    v4 = jnp.broadcast_to(v, lead + v.shape[-3:]).reshape((r, nh, t, dh))
    bias4 = jnp.broadcast_to(
        bias, lead + (nh, g, t)).reshape((r, nh, g, t)).astype(jnp.float32)
    out = _decode_attention(q4, k4, v4, bias4, float(scale), backend)
    return out.reshape(lead + (nh, g, dh))


@register_op("fused_decode_attention")
def _fused_decode_attention_op(ctx, ins, attrs):
    """Fused Q·K^T+bias→softmax→·V over a KV cache for a single-position
    query (emitted by `fuse_decode_attention_pass` from the 4-op decode
    chain)."""
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    bias = ins["Bias"][0]
    ks = ins.get("KScale")
    vs = ins.get("VScale")
    backend = attrs.get("backend") or _auto_backend()
    out = fused_decode_attention(q, k, v, bias,
                                 scale=attrs.get("scale", 1.0),
                                 backend=backend,
                                 k_scale=ks[0] if ks else None,
                                 v_scale=vs[0] if vs else None)
    return {"Out": [out]}
