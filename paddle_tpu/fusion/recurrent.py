"""Fused recurrent cells: the WHOLE LSTM/GRU recurrence as one Pallas kernel.

≙ reference operators/fusion_lstm_op.cc / fusion_gru_op.cc — the reference's
answer to the small-step problem: per-tick gate math fused into one kernel
instead of a chain of BLAS + elementwise launches. TPU translation goes one
step further: the kernel's grid iterates (batch-block, time) with the
hidden/cell state held in VMEM scratch across the sequential time steps
(TPU grid semantics, same mechanism as the flash kernel's online-softmax
accumulators), so the ENTIRE sequence is a single kernel launch — no
per-tick dispatch at all. The [B, T, 4H] input projections are computed
once outside (one big MXU matmul, exactly as `dynamic_lstm` already does);
what the kernel fuses is everything the unfused `lax.scan` body dispatched
per tick: the [H, 4H] recurrent matmul, four activations, the state update
and the sequence-length freeze.

Gradients: `jax.custom_vjp` with a manual reverse-time `lax.scan` against
gate activations stashed by the forward kernel — exact LSTM/GRU backward
(the math `jax.vjp` would derive from the unfused scan), so the fused ops
are drop-in for training graphs.

Gate orders match `ops/sequence_ops.py` exactly: LSTM (i, f, c_hat, o) on a
[H, 4H] recurrent weight, GRU (r, z | c) on [H, 3H] split as
w[:, :2H] / w[:, 2H:]. Sequence masking freezes state for finished rows
(`tpos < seqlen`), identical to the unfused lowerings.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..framework.registry import register_op

# batch rows per grid step; VMEM must hold x-block [bb, 4H] + w [H, 4H] +
# state scratch, so cap it (512 rows x 2048 gate lanes f32 = 4 MB)
_MAX_BATCH_BLOCK = 512


def _auto_backend():
    from ..ops.pallas_kernels import _auto_backend as _ab
    return _ab()


def _pallas_ok(x, w, hidden):
    """The Mosaic path needs lane-sliceable gate columns (128 | H) and f32
    compute; anything else takes the XLA composite (identical math)."""
    return (hidden % 128 == 0 and x.dtype == jnp.float32
            and w.dtype == jnp.float32)


def _resolve_backend(backend, x, w, hidden):
    backend = backend or _auto_backend()
    if backend in ("pallas", "pallas_interpret") and not _pallas_ok(
            x, w, hidden):
        from ..core import flags
        flags.vlog(1, "fused recurrent cell: shape (H=%d, dtype=%s) not "
                   "tile-aligned; using XLA composite", hidden, x.dtype)
        return "xla"
    return backend


def _round_up(n, m):
    return -(-n // m) * m


def _pad_rows(a, rows):
    if a.shape[0] == rows:
        return a
    pad = [(0, rows - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
    return jnp.pad(a, pad)


# ---------------------------------------------------------------------------
# Pallas whole-sequence kernels
# ---------------------------------------------------------------------------


def _lstm_seq_kernel(x_ref, sl_ref, h0_ref, c0_ref, w_ref, hs_ref, cs_ref,
                     g_ref, h_scr, c_scr, *, hidden, t_total, reverse):
    from jax.experimental import pallas as pl

    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = h0_ref[:].astype(jnp.float32)
        c_scr[:] = c0_ref[:].astype(jnp.float32)

    h_prev = h_scr[:]
    c_prev = c_scr[:]
    xt = x_ref[:, 0, :].astype(jnp.float32)                  # [bb, 4H]
    gates = xt + jax.lax.dot_general(
        h_prev, w_ref[:].astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    i = jax.nn.sigmoid(gates[:, :hidden])
    f = jax.nn.sigmoid(gates[:, hidden:2 * hidden])
    g = jnp.tanh(gates[:, 2 * hidden:3 * hidden])
    o = jax.nn.sigmoid(gates[:, 3 * hidden:])
    c_new = f * c_prev + i * g
    h_new = o * jnp.tanh(c_new)
    tpos = (t_total - 1 - t) if reverse else t
    valid = sl_ref[:, :1] > tpos                             # [bb, 1]
    h_new = jnp.where(valid, h_new, h_prev)
    c_new = jnp.where(valid, c_new, c_prev)
    h_scr[:] = h_new
    c_scr[:] = c_new
    hs_ref[:, 0, :] = h_new.astype(hs_ref.dtype)
    cs_ref[:, 0, :] = c_new.astype(cs_ref.dtype)
    if g_ref is not None:
        g_ref[:, 0, :hidden] = i
        g_ref[:, 0, hidden:2 * hidden] = f
        g_ref[:, 0, 2 * hidden:3 * hidden] = g
        g_ref[:, 0, 3 * hidden:] = o


def _gru_seq_kernel(x_ref, sl_ref, h0_ref, w_ref, hs_ref, g_ref, h_scr, *,
                    hidden, t_total, reverse):
    from jax.experimental import pallas as pl

    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = h0_ref[:].astype(jnp.float32)

    h_prev = h_scr[:]
    xt = x_ref[:, 0, :].astype(jnp.float32)                  # [bb, 3H]
    w = w_ref[:].astype(jnp.float32)
    rz = jax.nn.sigmoid(xt[:, :2 * hidden] + jax.lax.dot_general(
        h_prev, w[:, :2 * hidden], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32))
    r = rz[:, :hidden]
    z = rz[:, hidden:]
    c = jnp.tanh(xt[:, 2 * hidden:] + jax.lax.dot_general(
        r * h_prev, w[:, 2 * hidden:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32))
    h_new = z * h_prev + (1 - z) * c
    tpos = (t_total - 1 - t) if reverse else t
    valid = sl_ref[:, :1] > tpos
    h_new = jnp.where(valid, h_new, h_prev)
    h_scr[:] = h_new
    hs_ref[:, 0, :] = h_new.astype(hs_ref.dtype)
    if g_ref is not None:
        g_ref[:, 0, :hidden] = r
        g_ref[:, 0, hidden:2 * hidden] = z
        g_ref[:, 0, 2 * hidden:] = c


def _pallas_seq(kind, x, states0, w, seqlen, reverse, interpret, with_stash):
    """Run the whole-sequence kernel. x [B, T, G*H]; states0: (h0,) or
    (h0, c0); returns (hs[, cs][, stash])."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, t, gh = x.shape
    n_gates = 4 if kind == "lstm" else 3
    hidden = gh // n_gates
    bb = min(_round_up(b, 8), _MAX_BATCH_BLOCK)
    bp = _round_up(b, bb)
    nb = bp // bb

    xf = _pad_rows(x, bp)
    # seqlen rides broadcast over 128 lanes (a [B] vector output/input is
    # not Mosaic-tileable; same layout trick as the flash kernel's lse)
    slf = jnp.broadcast_to(
        _pad_rows(seqlen.astype(jnp.int32), bp)[:, None], (bp, 128))
    states = [_pad_rows(s, bp) for s in states0]

    grid = (nb, t)
    x_spec = pl.BlockSpec((bb, 1, gh), lambda bi, ti: (bi, ti, 0))
    sl_spec = pl.BlockSpec((bb, 128), lambda bi, ti: (bi, 0))
    s_spec = pl.BlockSpec((bb, hidden), lambda bi, ti: (bi, 0))
    w_spec = pl.BlockSpec(w.shape, lambda bi, ti: (0, 0))
    seq_spec = pl.BlockSpec((bb, 1, hidden), lambda bi, ti: (bi, ti, 0))
    g_spec = pl.BlockSpec((bb, 1, gh), lambda bi, ti: (bi, ti, 0))

    in_specs = [x_spec, sl_spec] + [s_spec] * len(states) + [w_spec]
    inputs = [xf, slf] + states + [w]
    n_state_outs = 2 if kind == "lstm" else 1
    out_specs = [seq_spec] * n_state_outs
    out_shape = [jax.ShapeDtypeStruct((bp, t, hidden), x.dtype)
                 for _ in range(n_state_outs)]
    if with_stash:
        out_specs.append(g_spec)
        out_shape.append(jax.ShapeDtypeStruct((bp, t, gh), jnp.float32))

    kern = (_lstm_seq_kernel if kind == "lstm" else _gru_seq_kernel)
    kern = functools.partial(kern, hidden=hidden, t_total=t, reverse=reverse)
    n_in = len(in_specs)
    n_out = n_state_outs + (1 if with_stash else 0)

    def body(*refs, _k=kern):
        ins, outs = refs[:n_in], refs[n_in:n_in + n_out]
        scratch = refs[n_in + n_out:]
        g_ref = outs[n_state_outs] if with_stash else None
        _k(*ins, *outs[:n_state_outs], g_ref, *scratch)

    scratch = [pltpu.VMEM((bb, hidden), jnp.float32)]
    if kind == "lstm":
        scratch.append(pltpu.VMEM((bb, hidden), jnp.float32))
    res = pl.pallas_call(
        body, grid=grid, in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shape, scratch_shapes=scratch,
        interpret=interpret)(*inputs)
    return tuple(r[:b] for r in res)


# ---------------------------------------------------------------------------
# XLA composite (identical math; also the <128-hidden / non-f32 path)
# ---------------------------------------------------------------------------


def _xla_lstm_seq(x, h0, c0, w, seqlen, reverse, with_stash):
    b, t, _ = x.shape

    def step(carry, inp):
        h_prev, c_prev = carry
        xt, it = inp
        gates = xt + jnp.dot(h_prev, w)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c_prev + i * g
        h_new = o * jnp.tanh(c_new)
        tpos = (t - 1 - it) if reverse else it
        valid = (tpos < seqlen)[:, None]
        h_new = jnp.where(valid, h_new, h_prev)
        c_new = jnp.where(valid, c_new, c_prev)
        stash = (jnp.concatenate([i, f, g, o], axis=-1)
                 if with_stash else jnp.zeros((0,), x.dtype))
        return (h_new, c_new), (h_new, c_new, stash)

    (_, _), (hs, cs, stash) = jax.lax.scan(
        step, (h0, c0), (jnp.swapaxes(x, 0, 1), jnp.arange(t)))
    out = (jnp.swapaxes(hs, 0, 1), jnp.swapaxes(cs, 0, 1))
    if with_stash:
        out = out + (jnp.swapaxes(stash, 0, 1),)
    return out


def _xla_gru_seq(x, h0, w, seqlen, reverse, with_stash):
    b, t, gh = x.shape
    h = gh // 3
    w_rz, w_c = w[:, :2 * h], w[:, 2 * h:]

    def step(h_prev, inp):
        xt, it = inp
        rz = jax.nn.sigmoid(xt[:, :2 * h] + jnp.dot(h_prev, w_rz))
        r, z = jnp.split(rz, 2, axis=-1)
        c = jnp.tanh(xt[:, 2 * h:] + jnp.dot(r * h_prev, w_c))
        h_new = z * h_prev + (1 - z) * c
        tpos = (t - 1 - it) if reverse else it
        valid = (tpos < seqlen)[:, None]
        h_new = jnp.where(valid, h_new, h_prev)
        stash = (jnp.concatenate([r, z, c], axis=-1)
                 if with_stash else jnp.zeros((0,), x.dtype))
        return h_new, (h_new, stash)

    _, (hs, stash) = jax.lax.scan(
        step, h0, (jnp.swapaxes(x, 0, 1), jnp.arange(t)))
    out = (jnp.swapaxes(hs, 0, 1),)
    if with_stash:
        out = out + (jnp.swapaxes(stash, 0, 1),)
    return out


def _run_lstm(x, h0, c0, w, seqlen, reverse, backend, with_stash):
    if backend == "xla":
        return _xla_lstm_seq(x, h0, c0, w, seqlen, reverse, with_stash)
    return _pallas_seq("lstm", x, [h0, c0], w, seqlen, reverse,
                       interpret=(backend == "pallas_interpret"),
                       with_stash=with_stash)


def _run_gru(x, h0, w, seqlen, reverse, backend, with_stash):
    if backend == "xla":
        return _xla_gru_seq(x, h0, w, seqlen, reverse, with_stash)
    return _pallas_seq("gru", x, [h0], w, seqlen, reverse,
                       interpret=(backend == "pallas_interpret"),
                       with_stash=with_stash)


# ---------------------------------------------------------------------------
# custom_vjp: manual reverse-time backward against the stashed activations
# ---------------------------------------------------------------------------


def _valid_mask(seqlen, t, reverse):
    pos = jnp.arange(t)
    if reverse:
        pos = t - 1 - pos
    return (pos[None, :] < seqlen[:, None])                  # [B, T]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _fused_lstm(x, h0, c0, w, seqlen, reverse, backend):
    return _run_lstm(x, h0, c0, w, seqlen, reverse, backend, False)[:2]


def _fused_lstm_fwd(x, h0, c0, w, seqlen, reverse, backend):
    hs, cs, stash = _run_lstm(x, h0, c0, w, seqlen, reverse, backend, True)
    return (hs, cs), (hs, cs, stash, h0, c0, w, seqlen)


def _fused_lstm_bwd(reverse, backend, res, grads):
    hs, cs, stash, h0, c0, w, seqlen = res
    dhs, dcs = grads
    b, t, h = hs.shape
    f32 = jnp.float32
    hprev = jnp.concatenate([h0[:, None], hs[:, :-1]], axis=1)
    cprev = jnp.concatenate([c0[:, None], cs[:, :-1]], axis=1)
    valid = _valid_mask(seqlen, t, reverse)[:, :, None]      # [B, T, 1]

    def tm(a):                                               # time-major
        return jnp.swapaxes(a.astype(f32), 0, 1)

    xs = (tm(dhs), tm(dcs), tm(stash), tm(hprev), tm(cprev), tm(cs),
          jnp.swapaxes(valid, 0, 1))

    def step(carry, inp):
        dh_c, dc_c, dw_acc = carry
        dh_out, dc_out, st, hp, cp, c_t, vd = inp
        i, f, g, o = jnp.split(st, 4, axis=-1)
        dh = dh_c + dh_out
        dc = dc_c + dc_out
        dh_v = jnp.where(vd, dh, 0.0)
        dc_v = jnp.where(vd, dc, 0.0)
        tc = jnp.tanh(c_t)
        do = dh_v * tc
        dc_v = dc_v + dh_v * o * (1.0 - tc * tc)
        di = dc_v * g
        dg = dc_v * i
        df = dc_v * cp
        dgates = jnp.concatenate(
            [di * i * (1 - i), df * f * (1 - f), dg * (1 - g * g),
             do * o * (1 - o)], axis=-1)
        dh_next = dgates @ w.astype(f32).T + jnp.where(vd, 0.0, dh)
        dc_next = dc_v * f + jnp.where(vd, 0.0, dc)
        dw_acc = dw_acc + hp.T @ dgates
        return (dh_next, dc_next, dw_acc), dgates

    init = (jnp.zeros((b, h), f32), jnp.zeros((b, h), f32),
            jnp.zeros(w.shape, f32))
    (dh0, dc0, dw), dx = jax.lax.scan(step, init, xs, reverse=True)
    dx = jnp.swapaxes(dx, 0, 1)
    return (dx.astype(hs.dtype), dh0.astype(h0.dtype), dc0.astype(c0.dtype),
            dw.astype(w.dtype), None)


_fused_lstm.defvjp(_fused_lstm_fwd, _fused_lstm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _fused_gru(x, h0, w, seqlen, reverse, backend):
    return _run_gru(x, h0, w, seqlen, reverse, backend, False)[0]


def _fused_gru_fwd(x, h0, w, seqlen, reverse, backend):
    hs, stash = _run_gru(x, h0, w, seqlen, reverse, backend, True)
    return hs, (hs, stash, h0, w, seqlen)


def _fused_gru_bwd(reverse, backend, res, dhs):
    hs, stash, h0, w, seqlen = res
    b, t, h = hs.shape
    f32 = jnp.float32
    wf = w.astype(f32)
    w_rz, w_c = wf[:, :2 * h], wf[:, 2 * h:]
    hprev = jnp.concatenate([h0[:, None], hs[:, :-1]], axis=1)
    valid = _valid_mask(seqlen, t, reverse)[:, :, None]

    def tm(a):
        return jnp.swapaxes(a.astype(f32), 0, 1)

    xs = (tm(dhs), tm(stash), tm(hprev), jnp.swapaxes(valid, 0, 1))

    def step(carry, inp):
        dh_c, dw_acc = carry
        dh_out, st, hp, vd = inp
        r, z, c = jnp.split(st, 3, axis=-1)
        dh = dh_c + dh_out
        dh_v = jnp.where(vd, dh, 0.0)
        dz = dh_v * (hp - c)
        dc = dh_v * (1.0 - z)
        dpre_c = dc * (1.0 - c * c)
        drh = dpre_c @ w_c.T
        dr = drh * hp
        dpre_r = dr * r * (1 - r)
        dpre_z = dz * z * (1 - z)
        dpre_rz = jnp.concatenate([dpre_r, dpre_z], axis=-1)
        dx_t = jnp.concatenate([dpre_rz, dpre_c], axis=-1)
        dh_next = (drh * r + dpre_rz @ w_rz.T + dh_v * z
                   + jnp.where(vd, 0.0, dh))
        dw_t = jnp.concatenate(
            [hp.T @ dpre_rz, (r * hp).T @ dpre_c], axis=-1)
        return (dh_next, dw_acc + dw_t), dx_t

    init = (jnp.zeros((b, h), f32), jnp.zeros(w.shape, f32))
    (dh0, dw), dx = jax.lax.scan(step, init, xs, reverse=True)
    dx = jnp.swapaxes(dx, 0, 1)
    return (dx.astype(hs.dtype), dh0.astype(h0.dtype), dw.astype(w.dtype),
            None)


_fused_gru.defvjp(_fused_gru_fwd, _fused_gru_bwd)


# ---------------------------------------------------------------------------
# public entry points + op registrations
# ---------------------------------------------------------------------------


def fused_lstm_sequence(x, h0, c0, w, seqlen, reverse=False, backend=None):
    """Whole-sequence fused LSTM. x [B, T, 4H] pre-projected (+bias),
    w [H, 4H] recurrent, seqlen [B] int; returns (hidden, cell) [B, T, H].
    Numerically equivalent to the `dynamic_lstm` scan (default
    activations), fwd and grad."""
    hidden = w.shape[0]
    backend = _resolve_backend(backend, x, w, hidden)
    if reverse:
        x = jnp.flip(x, axis=1)
    hs, cs = _fused_lstm(x, h0, c0, w, seqlen, bool(reverse), backend)
    if reverse:
        hs, cs = jnp.flip(hs, axis=1), jnp.flip(cs, axis=1)
    return hs, cs


def fused_gru_sequence(x, h0, w, seqlen, reverse=False, backend=None):
    """Whole-sequence fused GRU. x [B, T, 3H] pre-projected (+bias),
    w [H, 3H] (update/reset | candidate); returns hidden [B, T, H]."""
    hidden = w.shape[0]
    backend = _resolve_backend(backend, x, w, hidden)
    if reverse:
        x = jnp.flip(x, axis=1)
    hs = _fused_gru(x, h0, w, seqlen, bool(reverse), backend)
    if reverse:
        hs = jnp.flip(hs, axis=1)
    return hs


_DEFAULT_LSTM_ACTS = {"gate_activation": "sigmoid",
                      "cell_activation": "tanh",
                      "candidate_activation": "tanh"}
_DEFAULT_GRU_ACTS = {"gate_activation": "sigmoid", "activation": "tanh"}


def lstm_attrs_fusable(attrs) -> bool:
    return all(attrs.get(k, v) == v for k, v in _DEFAULT_LSTM_ACTS.items())


def gru_attrs_fusable(attrs) -> bool:
    return all(attrs.get(k, v) == v for k, v in _DEFAULT_GRU_ACTS.items())


@register_op("fused_lstm")
def _fused_lstm_op(ctx, ins, attrs):
    """Drop-in for `dynamic_lstm` (same slots/attrs, default activations
    only — `fuse_recurrent_cell_pass` rewrites only fusable instances)."""
    from ..core.enforce import InvalidArgumentError, enforce
    enforce(lstm_attrs_fusable(attrs),
            "fused_lstm supports only the default sigmoid/tanh activations",
            exc=InvalidArgumentError)
    x = ins["Input"][0]
    w = ins["Weight"][0]
    seqlen = ins["SeqLen"][0]
    h = w.shape[0]
    b = x.shape[0]
    bias = ins["Bias"][0] if ins.get("Bias") else None
    if bias is not None:
        x = x + bias.reshape(1, 1, -1)[:, :, :4 * h]
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((b, h), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((b, h), x.dtype)
    hs, cs = fused_lstm_sequence(
        x, h0, c0, w, seqlen, reverse=attrs.get("is_reverse", False),
        backend=attrs.get("backend"))
    return {"Hidden": [hs], "Cell": [cs]}


@register_op("fused_gru")
def _fused_gru_op(ctx, ins, attrs):
    """Drop-in for `dynamic_gru` (same slots/attrs, default activations)."""
    from ..core.enforce import InvalidArgumentError, enforce
    enforce(gru_attrs_fusable(attrs),
            "fused_gru supports only the default sigmoid/tanh activations",
            exc=InvalidArgumentError)
    x = ins["Input"][0]
    w = ins["Weight"][0]
    seqlen = ins["SeqLen"][0]
    h = w.shape[0]
    b = x.shape[0]
    if ins.get("Bias"):
        x = x + ins["Bias"][0].reshape(1, 1, -1)
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((b, h), x.dtype)
    hs = fused_gru_sequence(
        x, h0, w, seqlen, reverse=attrs.get("is_reverse", False),
        backend=attrs.get("backend"))
    return {"Hidden": [hs]}
