"""Operator-fusion subsystem: Pallas fused recurrent cells + fused
decode-attention step.

≙ the reference's fusion operators and fuse passes
(operators/fusion_lstm_op.cc, inference/analysis + framework/ir
attention_lstm_fuse_pass.cc): where the reference hand-fuses small
memory-bound op chains into single CUDA/CPU kernels, this package fuses the
two small-step hot paths VERDICT r5 identified as kernel-latency-floor
bound:

- `fused_lstm` / `fused_gru`: the WHOLE recurrence (every tick's gate
  matmul + activations + state update, sequence-length freezing included)
  runs as ONE Pallas kernel — grid over (batch blocks, time), hidden/cell
  state carried in VMEM scratch across the sequential time dimension — so
  the per-tick kernel dispatch floor behind stacked `dynamic_lstm` /
  `dynamic_gru` disappears. Training is supported via `jax.custom_vjp`
  (manual reverse-time scan against stashed gate activations).
- `fused_decode_attention`: one decode tick's QK^T·softmax·V over the
  KV cache — four ops (two matmuls, a bias add, a softmax) and their HBM
  round-trips of the [.., 1, T] score/weight tensors — in one kernel.
  The cache WRITE side stays on the existing `cache_write`
  dynamic-update-slice op.

Users normally never call these: the graph passes in
`framework/passes.py` (`fuse_recurrent_cell_pass`,
`fuse_decode_attention_pass`) pattern-match the op DAG and rewrite
matched subgraphs at executor-compile time, gated by the default-on
`fuse_recurrent_cells` / `fuse_decode_attention` flags
(kill switch: PTPU_FUSE_RECURRENT_CELLS=0 / PTPU_FUSE_DECODE_ATTENTION=0).

Backend selection mirrors ops/pallas_kernels.py: Pallas (Mosaic) on TPU
when shapes are tile-aligned, the mathematically identical XLA composite
elsewhere; "pallas_interpret" runs the kernels through the Pallas
interpreter so the CPU suite pins the same tiling logic the TPU runs.
"""

from .decode_attention import (dequantize_kv_time_blocks,  # noqa: F401
                               fused_decode_attention,
                               quantize_kv_time_blocks)
from .recurrent import (fused_gru_sequence,  # noqa: F401
                        fused_lstm_sequence)
