"""paddle_tpu — a TPU-native deep-learning framework with the capabilities of
PaddlePaddle Fluid (reference mounted at /root/reference; see SURVEY.md).

Public surface mirrors `paddle.fluid`: program-construction layers API,
append_backward autodiff, optimizers, Executor/ParallelExecutor, readers,
metrics, io — implemented TPU-first: programs trace to jax functions compiled
by XLA; parallelism is SPMD over a jax.sharding.Mesh with compiled collectives.
"""

from . import clip, initializer, layers, optimizer, regularizer  # noqa: F401
from .core import (CPUPlace, Place, TPUPlace, default_place,  # noqa: F401
                   device_count, devices, is_compiled_with_tpu)
from .core import flags  # noqa: F401
from .core import unique_name  # noqa: F401
from .framework.backward import append_backward, calc_gradient  # noqa: F401
from .framework.executor import Executor  # noqa: F401
from .framework.program import (Program, Variable, default_main_program,  # noqa: F401
                                default_startup_program, program_guard,
                                reset_default_programs)
from .framework.registry import registered_ops  # noqa: F401
from .framework.scope import Scope, global_scope, reset_global_scope  # noqa: F401
from .framework.selected_rows import SelectedRows  # noqa: F401
from .framework.passes import (Analyzer, Pass, get_pass,  # noqa: F401
                               register_pass, registered_passes)
from .framework.analysis import (analyze_program, check_program,  # noqa: F401
                                 infer_program, op_loc, verify_program)
from .param_attr import ParamAttr  # noqa: F401
from . import nets  # noqa: F401,E402
from . import models  # noqa: F401,E402
from . import io  # noqa: F401,E402
from . import sharded_checkpoint  # noqa: F401,E402
from .inferencer import Inferencer, Predictor  # noqa: F401,E402
from . import serving  # noqa: F401,E402
from . import serving_engine  # noqa: F401,E402
from . import metrics  # noqa: F401,E402
from . import observability  # noqa: F401,E402
from . import profiler  # noqa: F401,E402
from . import debugger  # noqa: F401,E402
from .trainer import (BeginEpochEvent, BeginStepEvent,  # noqa: F401,E402
                      CheckpointConfig, EndEpochEvent, EndStepEvent, Trainer,
                      load_checkpoint, save_checkpoint)
from .io import (load_inference_model, load_params,  # noqa: F401,E402
                 load_persistables, load_vars, save_inference_model,
                 save_params, save_persistables, save_vars)

__version__ = "0.1.0"
