"""Runtime shadow-state sanitizer for the paged KV / offload stack.

`PTPU_KV_SANITIZE=1` (pinned on in tests/conftest.py, exactly like
`PTPU_VERIFY_PASSES`) mirrors every real `BlockPool` / `KVPager` /
host-tier mutation into the abstract ownership model of
`framework/ownership.py` and raises `SanitizerDivergence` the moment
the real state and the model disagree — naming the op, the block and
the invariant. The model's preconditions fire BEFORE the real call, so
a protocol bug surfaces as its named diagnostic (`kv-double-free`,
`kv-write-shared-block`, ...) instead of a generic enforce assertion
three calls later.

Wiring: `KVPager.__init__` calls `attach(self)`; with the flag off
that returns None and the pager runs with ZERO per-op overhead (no
wrapper is installed — the kill switch is absence, not a branch).
With it on, the pool's alloc/share/release and the pager's
try_admit/fork/release/rollback/evict_table_to_host/
reload_table_from_host/refund_host_charge are wrapped on the
INSTANCE (class methods untouched — standalone `BlockPool` tests and
other pagers are unaffected), and the engine feeds the per-tick write
positions through `note_write` plus the h2d commit gate through
`note_h2d_commit`.

The sanitizer never touches the compiled tick program or any program
IR (pinned by tests/test_ownership.py's program-identity test) — but
the kill switch still joins the executor's compile cache key
(`_fusion_flags_key`), so a mid-process toggle can never share cached
compiled state with its instrumented twin.
"""

from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

from ..core import flags
from ..framework.ownership import (AbstractState, OwnershipViolation,
                                   TableState)

__all__ = ["ENV", "enabled", "attach", "KVSanitizer",
           "SanitizerDivergence"]

ENV = "PTPU_KV_SANITIZE"


def enabled() -> bool:
    """The kill switch of record is the `kv_sanitize` flag
    (core/flags.py); `PTPU_KV_SANITIZE=1` seeds it through the standard
    env bridge, and tests toggle it with `flags.set_flag` — the same
    discipline as `verify_passes`."""
    return bool(flags.get_flag("kv_sanitize"))


class SanitizerDivergence(OwnershipViolation):
    """The real pager state and the shadow model disagree — either a
    named protocol-invariant breach caught by the shadow's
    precondition, or a raw state mismatch (refcounts / free list /
    table map / host ledger). Subclasses `OwnershipViolation` (itself
    an `InvalidArgumentError`), so existing error-path tests keep
    passing while the message gains the op/block/invariant triple."""


def attach(pager) -> Optional["KVSanitizer"]:
    """Install the shadow on one `KVPager` iff the kill switch is on.
    Returns the sanitizer (also stored as `pager.sanitizer`) or None —
    callers gate per-tick mirroring on that None, which is what keeps
    the overhead-off budget at zero."""
    if not enabled():
        return None
    return KVSanitizer(pager)


def _index_pins(index) -> Dict[int, int]:
    """block -> pin multiplicity from a walk of the REAL radix tree
    (each node holds one index-owned retention ref on its block)."""
    pins: Dict[int, int] = {}
    stack = list(index.root.children.values())
    while stack:
        n = stack.pop()
        pins[n.block] = pins.get(n.block, 0) + 1
        stack.extend(n.children.values())
    return pins


class KVSanitizer:
    """The shadow: one `AbstractState` mirroring one `KVPager`.

    Pool primitives are mirrored per call (cheap integer updates +
    refcount/free-list equality); pager-level operations additionally
    maintain the shadow's table records and run the full invariant
    census (`verify_full`) — holder counts vs refcounts, the
    accounting identity, the two-tier ledger — after each one. Table
    records are keyed by `id(table)` and dropped on release, matching
    the real object lifetime."""

    def __init__(self, pager):
        self.pager = pager
        self.model = AbstractState(
            pager.pool.n_blocks, pager.pool.block_size,
            pager.host_tier.host_blocks if pager.host_tier else 0)
        self._detached_host = 0   # spill blocks released-but-unrefunded
        self._ctx: List[str] = []  # pager op naming the inner pool ops
        self.ops_mirrored = 0
        self.full_checks = 0
        self._wrap()
        pager.sanitizer = self

    # -- plumbing --------------------------------------------------------
    def _op(self, fallback: str) -> str:
        return self._ctx[-1] if self._ctx else fallback

    @contextmanager
    def _shadowed(self):
        """Every model call the shadow makes surfaces as a
        `SanitizerDivergence` (same code/op/block triple) — the caller
        sees ONE exception type for 'the live pager broke the
        protocol', whether the model's precondition or the census
        caught it."""
        try:
            yield
        except SanitizerDivergence:
            raise
        except OwnershipViolation as v:
            raise SanitizerDivergence(v.code, v.op, v.raw_message,
                                      block=v.block) from None

    def _diverge(self, code: str, op: str, message: str,
                 block: Optional[int] = None):
        raise SanitizerDivergence(
            code, op, "shadow-state divergence: " + message, block=block)

    def _cross_check_pool(self, op: str):
        pool = self.pager.pool
        if self.model.ref != pool._ref:
            bad = next(b for b in range(pool.n_blocks)
                       if self.model.ref[b] != pool._ref[b])
            self._diverge(
                "kv-accounting-identity", op,
                f"refcount mirror broke at block {bad}: model "
                f"{self.model.ref[bad]} vs pool {pool._ref[bad]}",
                block=bad)
        if self.model.free != set(pool._free) \
                or len(pool._free) != len(set(pool._free)):
            self._diverge(
                "kv-free-refcount", op,
                f"free-list mirror broke: model {sorted(self.model.free)} "
                f"vs pool {sorted(pool._free)}")

    def _rec(self, table, op: str) -> TableState:
        rec = self.model.tables.get(id(table))
        if rec is None:
            self._diverge(
                "kv-use-after-free", op,
                f"operation on a block table the shadow never saw "
                f"admitted or forked ({table!r})")
        return rec

    def _mirror_table(self, table, rec: TableState):
        rec.blocks = list(table.blocks)

    # -- instance wrapping ----------------------------------------------
    def _wrap(self):
        pool, pager = self.pager.pool, self.pager
        real_alloc = pool.alloc
        real_share = pool.share
        real_release = pool.release

        # the pool wrappers run a few times per tick under load, so like
        # note_write they use inline try/except instead of _shadowed.
        # The raw-mirror cross-check runs inline only for DIRECT pool
        # manipulation (empty ctx); inside a wrapped pager op the
        # boundary census (post_* -> verify_full) covers it
        def _lift(v):
            return SanitizerDivergence(v.code, v.op, v.raw_message,
                                       block=v.block)

        def alloc():
            b = real_alloc()
            if b is not None:
                self.ops_mirrored += 1
                op = self._op("pool.alloc")
                try:
                    self.model.alloc_at(b, op)
                except SanitizerDivergence:
                    raise
                except OwnershipViolation as v:
                    raise _lift(v) from None
                if not self._ctx:
                    self._cross_check_pool(op)
            return b

        def share(block):
            self.ops_mirrored += 1
            op = self._op("pool.share")
            try:
                self.model.share(block, op)  # named precondition FIRST
            except SanitizerDivergence:
                raise
            except OwnershipViolation as v:
                raise _lift(v) from None
            real_share(block)
            if not self._ctx:
                self._cross_check_pool(op)

        def release(block):
            self.ops_mirrored += 1
            op = self._op("pool.release")
            try:
                freed = self.model.release(block, op)
            except SanitizerDivergence:
                raise
            except OwnershipViolation as v:
                raise _lift(v) from None
            real_freed = real_release(block)
            if freed != real_freed:
                self._diverge(
                    "kv-free-refcount", op,
                    f"release of block {block}: model freed={freed} vs "
                    f"pool freed={real_freed}", block=block)
            if not self._ctx:
                self._cross_check_pool(op)
            return real_freed

        pool.alloc, pool.share, pool.release = alloc, share, release

        def wrap_ctx(name: str, post: Callable):
            real = getattr(pager, name)

            def wrapped(*args, **kwargs):
                self._ctx.append(name)
                try:
                    out = real(*args, **kwargs)
                finally:
                    self._ctx.pop()
                self.ops_mirrored += 1
                post(out, *args, **kwargs)
                return out
            setattr(pager, name, wrapped)

        def post_admit(table, prompt, need_len):
            if table is None:
                return
            rec = TableState(table.blocks, table.n_shared,
                             table.shared_len, len(prompt))
            self.model.tables[id(table)] = rec
            self.verify_full("try_admit")

        def post_fork(child, table, written_len, copy_block):
            parent = self._rec(table, "fork")
            rec = TableState(child.blocks, child.n_shared,
                             child.shared_len, parent.prompt_len)
            rec.written_len = int(written_len)
            rec.forked = True
            parent.forked = True
            self.model.tables[id(child)] = rec
            self.verify_full("fork")

        def post_release(out, table):
            rec = self.model.tables.pop(id(table), None)
            if rec is not None and rec.spilled:
                # the engine refunds the host charge separately
                # (_release_request -> refund_host_charge); until then
                # the ledger legitimately exceeds the live records
                self._detached_host += len(rec.spilled)
            self.verify_full("release")

        def post_rollback(n, table, keep_len, written_len):
            rec = self._rec(table, "rollback")
            self._mirror_table(table, rec)
            rec.written_len = int(keep_len)
            self.verify_full("rollback")

        def post_spill(spill_rec, table, written_len):
            if spill_rec is None:
                return                       # refused: no state change
            rec = self._rec(table, "evict_table_to_host")
            self._mirror_table(table, rec)
            rec.spilled = list(spill_rec.spilled)
            rec.arrived = not spill_rec.spilled
            with self._shadowed():
                self.model.host_charge(len(spill_rec.spilled),
                                       "evict_table_to_host")
            self.verify_full("evict_table_to_host")

        def post_reload(moves, table, spill_rec):
            if moves is None:
                return                       # rolled back: suspended
            rec = self._rec(table, "reload_table_from_host")
            self._mirror_table(table, rec)
            with self._shadowed():
                self.model.host_refund(len(spill_rec.spilled),
                                       "reload_table_from_host")
            rec.spilled = None
            rec.arrived = True
            self.verify_full("reload_table_from_host")

        def post_refund(out, n):
            if n > self._detached_host:
                self._diverge(
                    "kv-host-accounting", "refund_host_charge",
                    f"refund of {n} host blocks but only "
                    f"{self._detached_host} are pending from released "
                    f"spill records")
            self._detached_host -= n
            with self._shadowed():
                self.model.host_refund(n, "refund_host_charge")
            self.verify_full("refund_host_charge")

        wrap_ctx("try_admit", post_admit)
        wrap_ctx("fork", post_fork)
        wrap_ctx("release", post_release)
        wrap_ctx("rollback", post_rollback)
        wrap_ctx("evict_table_to_host", post_spill)
        wrap_ctx("reload_table_from_host", post_reload)
        wrap_ctx("refund_host_charge", post_refund)

        # pre-spill: the double-spill precondition must fire BEFORE the
        # real call (which would happily double-charge the host tier)
        real_spill = pager.evict_table_to_host

        def spill_guard(table, written_len):
            rec = self.model.tables.get(id(table))
            if rec is not None and rec.spilled is not None:
                raise SanitizerDivergence(
                    "kv-double-spill", "evict_table_to_host",
                    f"table is already host-resident (spilled blocks "
                    f"{rec.spilled})")
            return real_spill(table, written_len)

        pager.evict_table_to_host = spill_guard

    # -- engine-facing checks -------------------------------------------
    def note_write(self, table, pos: int):
        """One tick is about to write the cache row at token position
        `pos` of `table` (plain decode, beam slot, or one speculative
        verify lane). Enforces the CoW contract (target block refcount
        exactly 1, mapping live) against the shadow refcounts and keeps
        the shadow's write frontier.

        This is the sanitizer's hottest path — once per active request
        per tick — so the `_shadowed` contextmanager and the defensive
        list copy are inlined away (the only sanitizer code where that
        trade is worth it; see BENCH_KV_SANITIZE_r24.json)."""
        self.ops_mirrored += 1
        rec = self.model.tables.get(id(table))
        if rec is None:
            self._diverge(
                "kv-use-after-free", "tick-write",
                f"operation on a block table the shadow never saw "
                f"admitted or forked ({table!r})")
        blocks = table.blocks
        if rec.blocks != blocks:
            self._diverge(
                "kv-use-after-free", "tick-write",
                f"block-table mirror broke: model {rec.blocks} vs "
                f"table {list(blocks)}")
        try:
            self.model.note_write(blocks, pos, "tick-write")
        except SanitizerDivergence:
            raise
        except OwnershipViolation as v:
            raise SanitizerDivergence(v.code, v.op, v.raw_message,
                                      block=v.block) from None
        if pos >= rec.written_len:
            rec.written_len = pos + 1

    def note_h2d_commit(self, ticket):
        """The engine is about to scatter staged host content into the
        live cache arrays. The transfer ticket must have landed —
        committing an in-flight ticket is `kv-prefetch-after-use`
        (stale or torn rows under the scatter)."""
        self.ops_mirrored += 1
        if ticket is not None and not ticket.done():
            raise SanitizerDivergence(
                "kv-prefetch-after-use", "h2d-commit",
                "h2d commit with the transfer ticket still in flight "
                "— the scatter would write stale or torn rows")

    def verify_full(self, op: str = "verify"):
        """The census: every whole-state invariant of the model, with
        the pin multiplicities taken from a walk of the REAL radix
        tree, plus the raw mirrors (refcounts, free list, host ledger,
        index pin count) against the real pager."""
        self.full_checks += 1
        self._cross_check_pool(op)
        pins = _index_pins(self.pager.index)
        n_pins = sum(pins.values())
        if n_pins != self.pager.index.n_cached:
            self._diverge(
                "kv-block-leak", op,
                f"radix index holds {n_pins} pinned blocks but "
                f"n_cached says {self.pager.index.n_cached}")
        with self._shadowed():
            self.model.check_invariants(op=op, pins=pins,
                                        detached_host=self._detached_host)
        if self.model.host_used != self.pager.host_blocks_used:
            self._diverge(
                "kv-host-accounting", op,
                f"host ledger mirror broke: model "
                f"{self.model.host_used} vs pager "
                f"{self.pager.host_blocks_used}")

    def stats(self) -> Dict[str, int]:
        return {"ops_mirrored": self.ops_mirrored,
                "full_checks": self.full_checks,
                "tables_live": len(self.model.tables)}
