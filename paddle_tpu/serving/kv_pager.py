"""Paged KV cache with prefix sharing — the serving engine's block-table
memory manager (ISSUE r20 tentpole).

The slot engine (serving/engine.py) reserves one full [max_len] KV row
per slot — the serving-layer incarnation of the naive per-tensor
reservation the reference's L1 BuddyAllocator exists to kill (PAPER.md
§L1), and exactly the waste the r17 census prices in its `kv_cache`
category. This module replaces the per-slot rows with PAGES:

- `BlockPool` — host-side free-list + refcount accounting over ONE
  device-resident pool per layer per k/v ([n_blocks, nh, block_size,
  dh] persistable vars). Physical block 0 is the reserved NULL block:
  idle tick slots are steered to write there, and no live block table
  ever maps it.
- `BlockTable` — a request's logical→physical mapping: logical block j
  (token positions [j*block_size, (j+1)*block_size)) lives in physical
  block `blocks[j]`. Tables replace slot rows; a request holds exactly
  ceil((prompt+max_new)/block_size) blocks instead of max_len tokens.
- `RadixPrefixIndex` — block-granular prefix sharing: full
  `block_size`-token prompt blocks are registered (keyed by their token
  content) the moment their last row is written; a later request whose
  prompt starts with the same tokens maps its LEADING table entries to
  the SAME physical blocks (refcounted, zero prefill ticks for the
  shared span). Sharing is capped block-aligned at len(prompt)-1 so a
  write can NEVER land in a shared block and at least one prompt token
  remains to feed the tick. Cached blocks persist after their request
  completes (the index holds its own ref) and are evicted LRU
  LEAF-FIRST under pool pressure — evicting a mid-chain node would
  orphan its descendants' match path.
- Copy-on-write at the divergence block: `KVPager.fork` (beam search's
  hypothesis split) shares all fully-written blocks by refcount and
  EAGERLY copies the one partially-written block — the fork point — so
  each branch owns its divergence block before it writes there.
- `PagedKVEngine` — the ContinuousBatchingEngine subclass that decodes
  through all of the above: same scheduler/tick loop, but admission
  acquires a block table (head-of-line wait under pool pressure, with
  LRU eviction of cached prefixes), prefill SKIPS shared positions
  (compute is deterministic — the shared blocks hold byte-identical
  K/V, which is why decode is token-identical to the slot engine), and
  the compiled tick is `transformer_lm_paged_decode_tick` (gather by
  block table; the fused r06 decode-attention kernel matches the
  gathered view unchanged).
- `paged_beam_search` — beam decode over the paged engine: hypotheses
  share their common prefix physically (block refcounts), forks CoW the
  divergence block, and the per-tick top-k log-probs from the compiled
  tick drive host-side hypothesis selection.

Capacity math (the BENCH_SERVE_KV_r20 claim): at fixed pool bytes a
request pins ceil(L/block_size) blocks instead of max_len tokens, so
short/long-tail mixes admit ~max_len/L× more concurrency, and shared
prefixes reduce the marginal request to its PRIVATE blocks only.
Accounting is exact by construction: used + free == n_blocks - 1 (the
null block is neither) at every instant, and the census `kv_cache`
category (pool bytes) splits into the reserved/used watermark pair
(observability/memory.py channels `kv_cache_bytes` /
`kv_cache_used_bytes`).

Two-tier paging (ISSUE r23 tentpole): `PagedKVEngine(host_tier=
HostTierConfig(...))` extends the hierarchy one level down. Requests
keep being ADMITTED when the device pool is dry — they hold a tick
slot in a SUSPENDED state (zero bytes on either tier until they have
ticked) while the resident set decodes; a resident request's private
blocks can be EVICTED to the pinned host pool (d2h on the shared
transfer stream, overlapped with the next ticks — jax arrays are
immutable, so the snapshot the stream reads stays consistent after the
device blocks are rehandled) and PREFETCHED back `prefetch_distance`
ticks ahead of the projected resume (`offload.prefetch_issue_tick`,
the same helper `lint_program --offload` checks). Shared prefix-index
blocks are pinned on device — they are the highest-fanout bytes.
Per-slot decode is independent and deterministic, so suspend/resume
changes WHICH slots tick, never what any slot computes: two-tier
decode is token-identical to device-only decode (asserted by
tests/test_offload.py and BENCH_OFFLOAD_r23.json). The two-pool
accounting identity extends exactly: used_dev + used_host + free_dev +
free_host == (n_blocks - 1) + host_blocks (`KVPager.check_two_tier`).

Ownership verification (ISSUE r24): every mutation this module makes
is modeled declaratively in `framework/ownership.py` — the
depth-bounded model checker proves the protocol's invariants over all
op interleavings at small scope, and with `PTPU_KV_SANITIZE=1` the
runtime shadow (`serving/sanitizer.py`, attached in
`KVPager.__init__`) mirrors each real mutation into that model and
raises the named diagnostic on any divergence.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.enforce import InvalidArgumentError, enforce
from ..framework import offload as _offload
from ..framework.offload import HostTierConfig
from ..observability import memory as _obs_memory
from .engine import ContinuousBatchingEngine, GenRequest, _ENGINE_SEQ


class BlockPool:
    """Free-list + refcount accounting over the device block pool.

    Host-side only — the device arrays are the engine's persistable
    pool vars; this class decides WHICH physical block holds what.
    Block 0 is reserved as the null block (idle-slot write target): it
    is never on the free list and never allocated. Invariant, checked
    on demand via `check()`: n_used + n_free == n_blocks - 1, and a
    block is on the free list iff its refcount is 0."""

    def __init__(self, n_blocks: int, block_size: int):
        enforce(n_blocks >= 2,
                "pool needs at least 2 blocks (block 0 is the reserved "
                "null block)", exc=InvalidArgumentError)
        enforce(block_size >= 1, "block_size must be >= 1",
                exc=InvalidArgumentError)
        self.n_blocks = int(n_blocks)
        self.block_size = int(block_size)
        self._free = list(range(n_blocks - 1, 0, -1))   # LIFO: reuse hot
        self._ref = [0] * n_blocks                      # ref[0] stays 0

    def alloc(self) -> Optional[int]:
        """Take a free block (refcount 1); None when the pool is dry —
        the caller decides whether to evict or wait."""
        if not self._free:
            return None
        b = self._free.pop()
        self._ref[b] = 1
        return b

    def share(self, block: int):
        """One more holder of an allocated block (prefix share, beam
        fork, or the radix index's own retention ref)."""
        enforce(0 < block < self.n_blocks and self._ref[block] > 0,
                f"share of unallocated block {block}",
                exc=InvalidArgumentError)
        self._ref[block] += 1

    def release(self, block: int) -> bool:
        """Drop one ref; True when that freed the block (refcount hit
        0 and it returned to the free list)."""
        enforce(0 < block < self.n_blocks and self._ref[block] > 0,
                f"release of unallocated block {block}",
                exc=InvalidArgumentError)
        self._ref[block] -= 1
        if self._ref[block] == 0:
            self._free.append(block)
            return True
        return False

    def refcount(self, block: int) -> int:
        return self._ref[block]

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_used(self) -> int:
        return self.n_blocks - 1 - len(self._free)

    def check(self):
        """Assert the accounting identity (tests + CI reconciliation):
        used + free == n_blocks - 1, free iff refcount 0."""
        enforce(self.n_used + self.n_free == self.n_blocks - 1,
                f"pool accounting broken: used({self.n_used}) + "
                f"free({self.n_free}) != {self.n_blocks - 1}",
                exc=InvalidArgumentError)
        free = set(self._free)
        enforce(len(free) == len(self._free),
                "pool free list holds duplicates",
                exc=InvalidArgumentError)
        for b in range(1, self.n_blocks):
            enforce((self._ref[b] == 0) == (b in free),
                    f"block {b}: refcount {self._ref[b]} vs free-list "
                    f"membership {b in free}", exc=InvalidArgumentError)
        enforce(self._ref[0] == 0 and 0 not in free,
                "null block 0 must stay unallocated and off the free "
                "list", exc=InvalidArgumentError)


class BlockTable:
    """One request's logical→physical block mapping. `blocks[j]` is the
    physical home of token positions [j*block_size, (j+1)*block_size);
    the leading `n_shared` entries came from the prefix index (read-only
    to this request — writes start at `shared_len`)."""

    __slots__ = ("blocks", "n_shared", "shared_len")

    def __init__(self, blocks: List[int], n_shared: int = 0,
                 shared_len: int = 0):
        self.blocks = list(blocks)
        self.n_shared = int(n_shared)
        self.shared_len = int(shared_len)

    def __len__(self):
        return len(self.blocks)

    def __repr__(self):
        return (f"BlockTable(blocks={self.blocks}, "
                f"n_shared={self.n_shared})")


class _RadixNode:
    __slots__ = ("key", "block", "children", "parent", "last_used")

    def __init__(self, key, block, parent):
        self.key = key              # tuple of block_size token ids
        self.block = block          # physical block holding their K/V
        self.children: Dict[tuple, "_RadixNode"] = {}
        self.parent = parent
        self.last_used = 0


class RadixPrefixIndex:
    """Block-granular prompt-prefix index: a radix tree whose edges are
    FULL blocks of `block_size` tokens (a partial block is never
    sharable — its tail would be another request's garbage). Each node
    pins its physical block with one index-owned refcount, so cached
    prefixes survive their originating request until evicted. Matching
    walks children by exact token-tuple key; eviction is LRU over LEAF
    nodes only (a mid-chain eviction would break descendants' match
    paths while they still pin device blocks)."""

    def __init__(self, block_size: int):
        self.block_size = int(block_size)
        self.root = _RadixNode((), None, None)
        self._clock = 0
        self.n_cached = 0

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _keys(self, prompt: Sequence[int], n: int) -> List[tuple]:
        bs = self.block_size
        return [tuple(prompt[j * bs:(j + 1) * bs]) for j in range(n)]

    def match(self, prompt: Sequence[int]) -> List[_RadixNode]:
        """Longest chain of cached FULL blocks prefixing `prompt`
        (match order = logical block order). Bumps LRU clocks."""
        node, out = self.root, []
        for key in self._keys(prompt, len(prompt) // self.block_size):
            child = node.children.get(key)
            if child is None:
                break
            child.last_used = self._tick()
            out.append(child)
            node = child
        return out

    def register(self, prompt: Sequence[int], logical_block: int,
                 phys: int, pool: BlockPool) -> bool:
        """Offer block `logical_block` of `prompt` (physically `phys`,
        just fully written) to the cache. No-ops when the content chain
        already exists (a concurrent request filled the same prefix
        first — the existing copy stays canonical) or when an ancestor
        chain node is missing (evicted mid-flight — registering would
        orphan the new node's match path). On success the index takes
        its OWN ref on `phys`, so the block outlives its request."""
        node = self.root
        keys = self._keys(prompt, logical_block + 1)
        for j, key in enumerate(keys):
            child = node.children.get(key)
            if child is None:
                if j < logical_block:
                    return False            # broken ancestor chain
                child = _RadixNode(key, phys, node)
                node.children[key] = child
                pool.share(phys)            # the index's retention ref
                self.n_cached += 1
                child.last_used = self._tick()
                return True
            child.last_used = self._tick()
            node = child
        return False                        # full chain already cached

    def evict_one(self, pool: BlockPool) -> bool:
        """Evict the least-recently-used LEAF node (zero children),
        dropping the index's ref on its block — the block frees iff no
        live table still holds it. False when the index is empty."""
        victim = None
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif victim is None or n.last_used < victim.last_used:
                victim = n
        if victim is None:
            return False
        del victim.parent.children[victim.key]
        pool.release(victim.block)
        self.n_cached -= 1
        return True

    def evict_all(self, pool: BlockPool) -> int:
        n = 0
        while self.evict_one(pool):
            n += 1
        return n


class SpillRecord:
    """One suspended request's host-tier residency: which LOGICAL table
    entries were spilled (ascending), and how many host blocks they
    hold. The physical device ids they came from are dead the moment
    the spill releases them — only the engine's host buffers (keyed by
    the same ascending order) carry the content."""

    __slots__ = ("spilled", "n_blocks")

    def __init__(self, spilled: List[int], n_blocks: int):
        self.spilled = list(spilled)     # logical indices, ascending
        self.n_blocks = int(n_blocks)    # len(table.blocks) at spill


class KVPager:
    """The paged-KV policy engine: owns the BlockPool and the
    RadixPrefixIndex, makes the admission / share / CoW / release /
    eviction decisions, and keeps the counters the metrics registry
    exposes. Device bytes are the engine's; this is the brain.

    With `host_tier=HostTierConfig(...)` the pager also arbitrates the
    SECOND tier: `evict_table_to_host` trades a resident table's
    private device blocks for host-block capacity, and
    `reload_table_from_host` trades back. The pager still never
    touches bytes — the engine moves them on the transfer stream; this
    ledger only guarantees the two-pool identity
    used_dev + used_host + free_dev + free_host == total."""

    def __init__(self, n_blocks: int, block_size: int,
                 prefix_sharing: bool = True,
                 host_tier: Optional[HostTierConfig] = None):
        self.block_size = int(block_size)
        self.prefix_sharing = bool(prefix_sharing)
        self.pool = BlockPool(n_blocks, block_size)
        self.index = RadixPrefixIndex(block_size)
        self.host_tier = host_tier
        self.host_blocks_used = 0
        # -- counters (ptpu_engine_* gauges read these) --
        self.n_admitted = 0
        self.prefix_hits = 0            # admissions with shared_len > 0
        self.shared_blocks_total = 0    # table entries served by the index
        self.blocks_allocated_total = 0
        self.evictions = 0
        self.cow_copies = 0
        self.rolled_back_blocks = 0     # speculative-decode rejected spans
        self.host_evictions = 0         # blocks spilled device -> host
        self.host_reloads = 0           # spilled blocks reloaded h -> d
        self.host_prefetch_hits = 0     # resumes whose h2d had landed
        self.host_prefetch_misses = 0   # resumes that waited on the h2d
        # shadow-state sanitizer (PTPU_KV_SANITIZE=1): mirrors every
        # pool/pager mutation into the framework/ownership.py model and
        # raises the named diagnostic on divergence; None when off —
        # nothing is wrapped, so the off path costs nothing per op
        from . import sanitizer as _sanitizer
        self.sanitizer = _sanitizer.attach(self)

    # -- admission --------------------------------------------------------
    def blocks_needed(self, length: int) -> int:
        return -(-int(length) // self.block_size)

    def try_admit(self, prompt: Sequence[int],
                  need_len: int) -> Optional[BlockTable]:
        """Acquire a block table spanning `need_len` token positions for
        `prompt`, serving the leading blocks from the prefix cache when
        possible. None when the pool (after LRU eviction of cached
        prefixes) cannot cover the private remainder — the scheduler
        leaves the request at the head of the queue (no starvation).

        The shared span is capped at block-aligned len(prompt)-1: a
        request always keeps >= 1 prompt position to feed through the
        tick, and its first write lands in its first PRIVATE block —
        writes can never target shared blocks."""
        n_logical = self.blocks_needed(need_len)
        shared_nodes: List[_RadixNode] = []
        if self.prefix_sharing:
            shared_nodes = self.index.match(prompt)
        max_shared = (len(prompt) - 1) // self.block_size
        shared_nodes = shared_nodes[:min(max_shared, n_logical)]
        # pin the matched blocks FIRST: eviction under pressure below
        # may drop their index nodes, but a pinned block cannot free
        blocks = []
        for node in shared_nodes:
            self.pool.share(node.block)
            blocks.append(node.block)
        need_new = n_logical - len(shared_nodes)
        for _ in range(need_new):
            b = self._alloc_or_evict()
            if b is None:                    # rollback, stay pending
                for held in blocks:
                    self.pool.release(held)
                return None
            blocks.append(b)
        n_shared = len(shared_nodes)
        self.n_admitted += 1
        self.blocks_allocated_total += need_new
        if n_shared:
            self.prefix_hits += 1
            self.shared_blocks_total += n_shared
        return BlockTable(blocks, n_shared, n_shared * self.block_size)

    def _alloc_or_evict(self) -> Optional[int]:
        while True:
            b = self.pool.alloc()
            if b is not None:
                return b
            if not self.index.evict_one(self.pool):
                return None
            self.evictions += 1

    # -- lifecycle --------------------------------------------------------
    def note_block_filled(self, table: BlockTable, logical_block: int,
                          prompt: Sequence[int]):
        """Block `logical_block` of the request just received its last
        row. If it is a FULL prompt block (generated tokens are not
        shareable prefix — they differ per request even for equal
        prompts under different max_new/eos) and not itself served from
        the index, offer it to the prefix cache NOW: a request arriving
        mid-prefill of its twin already shares the finished span."""
        if not self.prefix_sharing or logical_block < table.n_shared:
            return
        if (logical_block + 1) * self.block_size > len(prompt):
            return
        self.index.register(prompt, logical_block,
                            table.blocks[logical_block], self.pool)

    def fork(self, table: BlockTable, written_len: int,
             copy_block: Callable[[int, int], None]) -> BlockTable:
        """Split a hypothesis (beam search): the fork shares every FULLY
        written block by refcount, COPY-ON-WRITES the one partially
        written block (the divergence block — `copy_block(src, dst)`
        moves its device bytes), and takes fresh private blocks for the
        not-yet-written remainder. Raises when the pool cannot cover
        the fork even after eviction."""
        n_full, rem = divmod(int(written_len), self.block_size)
        blocks: List[int] = []
        try:
            for j, b in enumerate(table.blocks):
                if j < n_full:
                    self.pool.share(b)
                    blocks.append(b)
                    continue
                nb = self._alloc_or_evict()
                if nb is None:
                    raise InvalidArgumentError(
                        f"block pool exhausted forking at block {j} "
                        f"({self.pool.n_free} free of "
                        f"{self.pool.n_blocks - 1})")
                if j == n_full and rem:
                    copy_block(b, nb)        # CoW at the divergence block
                    self.cow_copies += 1
                blocks.append(nb)
                self.blocks_allocated_total += 1
        except Exception:
            for held in blocks:
                self.pool.release(held)
            raise
        return BlockTable(blocks, table.n_shared, table.shared_len)

    def release(self, table: BlockTable):
        """Drop the table's ref on every LIVE mapping (completion or
        fork retirement). Blocks the prefix index also holds stay
        resident (cached) until evicted; everything else frees. Dead
        (zeroed) mappings — a table released while its content is
        host-resident, the drain/shutdown path — are skipped: their
        device refs were already traded for the host charge at spill
        time (the caller refunds that via `refund_host_charge`)."""
        for b in table.blocks:
            if b:
                self.pool.release(b)
        table.blocks = []

    def rollback(self, table: BlockTable, keep_len: int,
                 written_len: int) -> int:
        """Roll back the table entries whose EVERY position lies in a
        speculative round's rejected span [keep_len, written_len):
        release the dirty block and remap the entry to a fresh one. The
        boundary block holding position keep_len-1 stays — its rejected
        tail is dead under the position mask and the next round's writes
        land on it before it is ever exposed.

        Written blocks are always PRIVATE (writes never target shared
        blocks — try_admit caps the shared span below the first write),
        so each release frees its block; allocating right after can
        therefore never come up dry (release-first guarantees the pool
        holds at least the block just freed). Both halves are enforced:
        a refcounted rollback block or a failed realloc is an invariant
        breach, not a condition to handle."""
        bs = self.block_size
        first = -(-int(keep_len) // bs)          # first fully-rejected block
        last = (int(written_len) - 1) // bs      # last written block
        n = 0
        for j in range(first, min(last + 1, len(table.blocks))):
            freed = self.pool.release(table.blocks[j])
            enforce(freed,
                    f"speculative rollback hit shared block "
                    f"{table.blocks[j]} (logical {j}) — writes must "
                    f"never land in shared blocks",
                    exc=InvalidArgumentError)
            nb = self.pool.alloc()
            enforce(nb is not None, "alloc after release came up dry",
                    exc=InvalidArgumentError)
            table.blocks[j] = nb
            n += 1
        self.rolled_back_blocks += n
        return n

    # -- two-tier (host) lifecycle -----------------------------------------
    def evict_table_to_host(self, table: BlockTable,
                            written_len: int) -> Optional[SpillRecord]:
        """Suspend a resident table: release every PRIVATE device block
        back to the pool and charge the CONTENT-bearing ones (logical
        blocks covering positions [shared_len, written_len)) to the
        host tier. Shared prefix blocks keep their refs — they are
        pinned on device (highest-fanout bytes; HostTierConfig.
        pin_index_nodes). Returns None — spill refused — when the host
        tier cannot hold the content; otherwise the SpillRecord the
        engine needs to know which logical entries to snapshot.

        Private blocks must free on release (writes never land in
        shared blocks — the same invariant `rollback` enforces); a
        refcounted private block here is a breach, not a condition."""
        enforce(self.host_tier is not None,
                "evict_table_to_host without a host tier",
                exc=InvalidArgumentError)
        bs = self.block_size
        n_content = -(-int(written_len) // bs)   # blocks with live rows
        spilled = [j for j in range(table.n_shared,
                                    min(n_content, len(table.blocks)))]
        if self.host_blocks_used + len(spilled) \
                > self.host_tier.host_blocks:
            return None
        for j in range(table.n_shared, len(table.blocks)):
            # full prompt blocks may ALSO be held by the prefix index
            # (note_block_filled registered them) — releasing our ref
            # then leaves them device-resident as cache, possibly
            # evicted later. The engine snapshots the content to host
            # either way, so resume never depends on the index's whim.
            self.pool.release(table.blocks[j])
            table.blocks[j] = 0          # dead mapping until reload
        self.host_blocks_used += len(spilled)
        self.host_evictions += len(spilled)
        return SpillRecord(spilled, len(table.blocks))

    def reload_table_from_host(self, table: BlockTable,
                               rec: SpillRecord
                               ) -> Optional[List[Tuple[int, int]]]:
        """Resume a suspended table: re-allocate a device block for
        every private logical entry (evicting cached prefixes LRU under
        pressure, exactly like admission) and release the host-tier
        charge. Returns [(logical_j, new_physical)] for the
        CONTENT-bearing entries — the h2d copy list, in the
        SpillRecord's ascending order — or None (everything rolled
        back, host charge untouched) when the device pool cannot cover
        the resume yet."""
        enforce(len(table.blocks) == rec.n_blocks,
                f"spill record spans {rec.n_blocks} blocks but the "
                f"table has {len(table.blocks)}",
                exc=InvalidArgumentError)
        got: List[int] = []
        for j in range(table.n_shared, len(table.blocks)):
            b = self._alloc_or_evict()
            if b is None:                # roll back, stay suspended
                for held in got:
                    self.pool.release(held)
                return None
            got.append(b)
        for j, b in zip(range(table.n_shared, len(table.blocks)), got):
            table.blocks[j] = b
        self.host_blocks_used -= len(rec.spilled)
        self.host_reloads += len(rec.spilled)
        self.blocks_allocated_total += len(got)
        return [(j, table.blocks[j]) for j in rec.spilled]

    def refund_host_charge(self, n: int):
        """Return `n` host-tier blocks whose spill will never reload —
        a request released while host-resident (drain/shutdown). A
        pager METHOD (not a raw ledger write) so the shadow-state
        sanitizer can mirror the refund and hold the two-tier identity
        through it."""
        enforce(0 <= n <= self.host_blocks_used,
                f"host refund of {n} blocks underflows the ledger "
                f"({self.host_blocks_used} used)",
                exc=InvalidArgumentError)
        self.host_blocks_used -= n

    def check_two_tier(self):
        """The r23 accounting identity over BOTH tiers (the ISSUE's
        `used_dev + used_host + free == total`), on top of the device
        pool's own refcount/free-list exactness (`BlockPool.check`)."""
        self.pool.check()
        cap = self.host_tier.host_blocks if self.host_tier else 0
        enforce(0 <= self.host_blocks_used <= cap,
                f"host tier accounting broken: {self.host_blocks_used} "
                f"used of {cap}", exc=InvalidArgumentError)
        used_dev, free_dev = self.pool.n_used, self.pool.n_free
        used_host = self.host_blocks_used
        free_host = cap - used_host
        total = (self.pool.n_blocks - 1) + cap
        enforce(used_dev + used_host + free_dev + free_host == total,
                f"two-tier identity broken: {used_dev}+{used_host}+"
                f"{free_dev}+{free_host} != {total}",
                exc=InvalidArgumentError)

    # -- introspection ----------------------------------------------------
    def stats(self) -> Dict:
        return {
            "n_blocks": self.pool.n_blocks,
            "block_size": self.block_size,
            "blocks_used": self.pool.n_used,
            "blocks_free": self.pool.n_free,
            "blocks_cached": self.index.n_cached,
            "prefix_sharing": self.prefix_sharing,
            "admitted": self.n_admitted,
            "prefix_hits": self.prefix_hits,
            "prefix_hit_rate": (self.prefix_hits / self.n_admitted
                                if self.n_admitted else 0.0),
            "shared_blocks_total": self.shared_blocks_total,
            "blocks_allocated_total": self.blocks_allocated_total,
            "blocks_per_request": (self.blocks_allocated_total
                                   / self.n_admitted
                                   if self.n_admitted else 0.0),
            "evictions": self.evictions,
            "cow_copies": self.cow_copies,
            "rolled_back_blocks": self.rolled_back_blocks,
            "host_tier": None if self.host_tier is None else {
                "host_blocks": self.host_tier.host_blocks,
                "host_blocks_used": self.host_blocks_used,
                "prefetch_distance": self.host_tier.prefetch_distance,
                "rotate_quantum": self.host_tier.rotate_quantum,
                "host_evictions": self.host_evictions,
                "host_reloads": self.host_reloads,
                "prefetch_hits": self.host_prefetch_hits,
                "prefetch_misses": self.host_prefetch_misses,
                "prefetch_hit_rate": (
                    self.host_prefetch_hits
                    / max(self.host_prefetch_hits
                          + self.host_prefetch_misses, 1)),
            },
        }


class PagedKVEngine(ContinuousBatchingEngine):
    """Continuous batching over the paged KV cache: the slot engine's
    scheduler and tick loop, with the per-slot [max_len] KV rows
    replaced by block tables over one shared pool.

    What changes vs the parent (every override is one of the parent's
    named hooks — the scheduler itself is untouched, which is what
    makes the decode-identity guarantee auditable):

    - the compiled tick is `transformer_lm_paged_decode_tick` (gather
      by block table + `paged_cache_write`; same attention chain, same
      fused decode kernel);
    - admission acquires a BlockTable from the `KVPager` (head-of-line
      wait under pool pressure — `_admit_request` returning False);
      prefix hits start the request at `fed = shared_len`, skipping the
      shared span's prefill ticks entirely;
    - completion releases the table; full prompt blocks were offered to
      the prefix index the moment they filled (`_note_position_written`);
    - the KV watermarks split honestly: reserved = pool bytes (pinned),
      used = allocated blocks × block bytes (live paging state);
    - `max_len` means the block-table SPAN (blocks_per_req×block_size —
      the per-request logical ceiling), not a per-slot reservation:
      n_blocks is free to be far smaller than n_slots×blocks_per_req,
      which is the whole capacity play.

    `topk_k` > 0 additionally fetches each tick's top-k log-probs —
    `paged_beam_search`'s scoring surface (greedy serving leaves it 0).
    """

    def __init__(self, n_slots: int = 4, vocab: int = 32000,
                 max_len: int = 64, d_model: int = 512,
                 d_inner: int = 2048, num_heads: int = 8,
                 num_layers: int = 6, dropout: float = 0.0,
                 packed: bool = False, eos_id: Optional[int] = None,
                 scope=None, policy: str = "continuous",
                 cache_prefix: Optional[str] = None, block_size: int = 8,
                 n_blocks: Optional[int] = None,
                 prefix_sharing: bool = True, topk_k: int = 0,
                 quant: Optional[str] = None, kv_quant: bool = False,
                 speculative=None,
                 host_tier: Optional[HostTierConfig] = None):
        enforce(host_tier is None or speculative is None,
                "host_tier does not compose with speculative decoding "
                "yet: a speculative round's rollback remaps blocks the "
                "suspend/resume swap may hold in flight on the stream — "
                "pager-level rollback composition IS covered "
                "(tests/test_offload.py); pick one per engine",
                exc=InvalidArgumentError)
        if host_tier is not None:
            enforce(isinstance(host_tier, HostTierConfig),
                    f"host_tier must be a HostTierConfig, got "
                    f"{type(host_tier).__name__}",
                    exc=InvalidArgumentError)
        self.host_tier = host_tier
        self.block_size = int(block_size)
        self.blocks_per_req = -(-int(max_len) // self.block_size)
        self.prefix_sharing = bool(prefix_sharing)
        self.topk_k = int(topk_k)
        self.kv_quant = bool(kv_quant)
        # int8 KV block pools (ROADMAP item 2's remaining leg): the pool
        # payload is int8 with one f32 scale per (block, head, row), so a
        # block costs bytes_int8 = nh*bs*(dh+4) instead of nh*bs*dh*4 per
        # k/v per layer. At the SAME byte budget the freed bytes buy
        # extra admitted blocks: the capacity-neutral default n_blocks is
        # scaled up by bytes_f32/bytes_int8 (an explicit n_blocks is
        # honored as-is — the caller owns the budget then).
        dh = d_model // num_heads
        per_blk_f32 = 2 * num_layers * num_heads * self.block_size * dh * 4
        per_blk_i8 = 2 * num_layers * num_heads * self.block_size * (dh + 4)
        self.kv_quant_freed_bytes = 0
        if n_blocks is None:
            # capacity-neutral default: every slot can hold a full-span
            # request (+ null block) — callers size DOWN from here to
            # realize the paging win at fixed bytes
            n_blocks = n_slots * self.blocks_per_req + 1
            if self.kv_quant:
                budget = (n_blocks - 1) * per_blk_f32
                n_blocks = 1 + budget // per_blk_i8
        if self.kv_quant:
            self.kv_quant_freed_bytes = \
                (int(n_blocks) - 1) * (per_blk_f32 - per_blk_i8)
        self.n_blocks = int(n_blocks)
        enforce(self.n_blocks >= self.blocks_per_req + 1,
                f"pool of {self.n_blocks} blocks cannot hold one "
                f"full-span request ({self.blocks_per_req} blocks + the "
                f"null block)", exc=InvalidArgumentError)
        self.pager = KVPager(self.n_blocks, self.block_size,
                             prefix_sharing, host_tier=host_tier)
        # two-tier scheduler state: per-rid host residency records and
        # the FIFO of suspended requests (admission order — no
        # starvation, same discipline as the head-of-line device wait)
        self._ht_state: Dict[int, Dict] = {}
        self._ht_queue: List[GenRequest] = []
        self._ht_stream = _offload.shared_stream() \
            if host_tier is not None else None
        self._ht_pool = _offload.shared_host_pool() \
            if host_tier is not None else None
        self._ht_per_block_bytes = 0     # measured lazily (first spill)
        self.ht_d2h_bytes = 0            # measured: actual buffer bytes
        self.ht_h2d_bytes = 0
        if cache_prefix is None:
            cache_prefix = f"pgd{next(_ENGINE_SEQ)}"
        super().__init__(
            n_slots=n_slots, vocab=vocab,
            max_len=self.blocks_per_req * self.block_size,
            d_model=d_model, d_inner=d_inner, num_heads=num_heads,
            num_layers=num_layers, dropout=dropout, packed=packed,
            eos_id=eos_id, scope=scope, policy=policy,
            cache_prefix=cache_prefix, quant=quant,
            speculative=speculative)

    # -- tick program -----------------------------------------------------
    def _build_tick_program(self, n_slots, vocab, max_len, d_model,
                            d_inner, num_heads, num_layers, dropout,
                            packed, cache_prefix):
        from ..models import transformer
        outs = transformer.transformer_lm_paged_decode_tick(
            n_slots=n_slots, n_blocks=self.n_blocks,
            block_size=self.block_size,
            blocks_per_req=self.blocks_per_req, vocab=vocab,
            d_model=d_model, d_inner=d_inner, num_heads=num_heads,
            num_layers=num_layers, dropout=dropout, packed=packed,
            cache_prefix=cache_prefix, topk_k=self.topk_k,
            kv_quant=self.kv_quant)
        if self.topk_k:
            (self._next_ids, self.cache_names,
             self._topk_logp, self._topk_ids) = outs
        else:
            self._next_ids, self.cache_names = outs

    def _init_tick_feeds(self) -> Dict[str, np.ndarray]:
        f = super()._init_tick_feeds()
        f["tick_btab"] = np.zeros((self.n_slots, self.blocks_per_req),
                                  np.int64)
        f["tick_wblock"] = np.zeros((self.n_slots,), np.int64)
        f["tick_woff"] = np.zeros((self.n_slots,), np.int64)
        return f

    def _tick_fetches(self):
        if self.topk_k:
            return [self._next_ids, self._topk_logp, self._topk_ids]
        return [self._next_ids]

    def _fill_tick_feeds(self, active: Dict[int, GenRequest]):
        super()._fill_tick_feeds(active)        # tok/pos rows
        btab = self._feeds["tick_btab"]
        wblock = self._feeds["tick_wblock"]
        woff = self._feeds["tick_woff"]
        btab[:] = 0                              # idle slots → null block
        wblock[:] = 0
        woff[:] = 0
        bs = self.block_size
        for slot, req in active.items():
            blocks = req.table.blocks
            btab[slot, :len(blocks)] = blocks
            lb, off = divmod(req.fed, bs)
            wblock[slot] = blocks[lb]
            woff[slot] = off

    def _note_tick_writes(self, active: Dict[int, GenRequest]):
        # shadow-state sanitizer: every position this tick writes must
        # target a live, EXCLUSIVELY-held block (the CoW contract) —
        # checked against the ownership model before dispatch
        san = self.pager.sanitizer
        if san is not None:
            for req in active.values():
                san.note_write(req.table, req.fed)

    # -- scheduler hooks --------------------------------------------------
    def _admit_request(self, req: GenRequest) -> bool:
        need_len = min(len(req.prompt) + req.max_new, self.max_len)
        table = self.pager.try_admit(req.prompt, need_len)
        if table is not None:
            req.table = table
            req.shared_len = table.shared_len
            if table.shared_len:
                # the shared span's K/V is already resident and
                # byte-exact (deterministic compute) — skip its
                # prefill ticks
                req.fed = table.shared_len
                req.next_tok = req.prompt[table.shared_len]
            if self.host_tier is not None:
                self._ht_state[req.rid] = {"state": "resident",
                                           "resume_tick": self.n_ticks}
            return True
        if self.host_tier is None:
            return False                         # head-of-line wait
        # two-tier admission: the device pool is dry but tick slots are
        # not — admit SUSPENDED. The request holds its slot with ZERO
        # bytes on either tier (it has never ticked); it starts decoding
        # when a resident finishes or the rotation quantum frees blocks.
        # This is exactly where admitted concurrency beats the
        # device-only ceiling (BENCH_OFFLOAD_r23.json).
        req.table = None
        self._ht_state[req.rid] = {"state": "waiting",
                                   "spill": None, "bufs": None,
                                   "d2h": None, "h2d": None,
                                   "suspend_tick": self.n_ticks}
        self._ht_queue.append(req)
        return True

    def _release_request(self, req: GenRequest):
        if req.table is not None:
            self.pager.release(req.table)
            req.table = None
        st = self._ht_state.pop(req.rid, None)
        if st is not None and st.get("bufs"):
            # a request released while host-resident (drain/shutdown):
            # its spill never reloads — return the host bytes
            if st.get("d2h") is not None:
                st["d2h"].wait(timeout=60.0)
            for buf in st["bufs"].values():
                self._ht_pool.free(buf)
            self.pager.refund_host_charge(len(st["spill"].spilled))
        if st is not None and req in self._ht_queue:
            self._ht_queue.remove(req)

    def _note_position_written(self, req: GenRequest, pos: int):
        if (pos + 1) % self.block_size == 0:
            self.pager.note_block_filled(req.table,
                                         pos // self.block_size,
                                         req.prompt)

    # -- two-tier scheduler (host_tier=) ----------------------------------
    @staticmethod
    def _remaining_ticks(req: GenRequest) -> int:
        """Upper bound on ticks until `req` finishes (eos can only
        shorten it — a prefetch issued against this bound can be late,
        never early; late shows up honestly as a prefetch miss)."""
        prefill = max(0, len(req.prompt) - 1 - req.fed)
        return prefill + max(0, req.max_new - len(req.tokens))

    def _pre_tick(self, active: Dict[int, GenRequest]
                  ) -> Dict[int, GenRequest]:
        """The swap scheduler, run between ticks on the compute thread
        (the single sanctioned writer of the donated cache arrays —
        `PreparedStep.refresh_state` re-points the bound step after
        commits). In order: resume waiters FIFO while the device pool
        covers them; rotate (evict the resident with the most remaining
        work) when the head waiter has starved a full quantum; issue
        h2d prefetches `prefetch_distance` ticks ahead of the projected
        resume. Returns the RESIDENT subset — suspended requests hold
        their slots but do not tick."""
        if self.host_tier is None:
            return active
        tick = self.n_ticks
        while self._ht_queue and self._try_resume(self._ht_queue[0]):
            self._ht_queue.pop(0)
        quantum = self.host_tier.rotate_quantum
        if self._ht_queue and quantum:
            head = self._ht_queue[0]
            if tick - self._ht_state[head.rid]["suspend_tick"] >= quantum:
                victim = self._pick_victim(active, tick)
                if victim is not None:
                    self._suspend_resident(victim, tick)
                    if self._try_resume(head):
                        self._ht_queue.pop(0)
        self._maybe_prefetch(active, tick)
        resident = {s: r for s, r in active.items()
                    if self._ht_state[r.rid]["state"] == "resident"}
        if not resident and active:
            # nothing resident can only mean the pool is all free or
            # index-cached — the head waiter MUST resume (else the
            # two-tier scheduler would deadlock; make that loud)
            head = self._ht_queue[0]
            enforce(self._try_resume(head),
                    "two-tier scheduler wedged: no resident requests "
                    "and the head waiter cannot acquire device blocks",
                    exc=InvalidArgumentError)
            self._ht_queue.pop(0)
            resident = {s: r for s, r in active.items()
                        if self._ht_state[r.rid]["state"] == "resident"}
        return resident

    def _try_resume(self, req: GenRequest) -> bool:
        """Make a suspended request resident: never-ticked waiters go
        through normal admission (prefix sharing included); spilled
        waiters re-acquire device blocks and commit their staged h2d
        content. False = capacity still short, stay queued."""
        st = self._ht_state[req.rid]
        if st["state"] == "waiting":
            need_len = min(len(req.prompt) + req.max_new, self.max_len)
            table = self.pager.try_admit(req.prompt, need_len)
            if table is None:
                return False
            req.table = table
            req.shared_len = table.shared_len
            if table.shared_len:
                req.fed = table.shared_len
                req.next_tok = req.prompt[table.shared_len]
        else:                                    # spilled, has content
            moves = self.pager.reload_table_from_host(req.table,
                                                      st["spill"])
            if moves is None:
                return False
            if moves:
                if st.get("d2h") is not None:
                    # surfaces a failed spill copy here instead of
                    # letting a zeroed host buffer reach the cache
                    st["d2h"].wait(timeout=60.0)
                ticket = st.get("h2d")
                hit = ticket is not None and ticket.done()
                if ticket is None:
                    ticket = self._stage_h2d(st)
                self.pager.host_prefetch_hits += 1 if hit else 0
                self.pager.host_prefetch_misses += 0 if hit else 1
                _offload.note_prefetch(hit)
                staged = ticket.wait(timeout=60.0)
                san = self.pager.sanitizer
                if san is not None:
                    # prefetch-after-use gate: the wait() above must
                    # have landed the ticket before the scatter commits
                    san.note_h2d_commit(ticket)
                self._commit_h2d(moves, staged)
                self.ht_h2d_bytes += ticket.nbytes
            for buf in (st["bufs"] or {}).values():
                self._ht_pool.free(buf)
            st.update(spill=None, bufs=None, d2h=None, h2d=None)
        st.update(state="resident", resume_tick=self.n_ticks)
        return True

    def _suspend_resident(self, req: GenRequest, tick: int):
        """Evict a resident request's private blocks to the host tier:
        the pager trades the device blocks for host capacity, the
        engine gathers the spilled rows out of the cache arrays HERE,
        on the compute thread, before the next tick can run — the r21
        donated decode tick hands the cache buffers back to XLA every
        dispatch, so a lazily-captured array may be backing a reused
        buffer by the time a stream thread reads it (observed: silent
        zeros, not an error). Only the host-side copy into the pinned
        pool buffer rides the transfer stream; that copy is what the
        d2h byte accounting and the `offload` span measure."""
        st = self._ht_state[req.rid]
        table = req.table
        phys = {j: table.blocks[j] for j in range(len(table.blocks))}
        rec = self.pager.evict_table_to_host(table, req.fed)
        if rec is None:
            return                               # host tier full: keep
        st.update(state="spilled", spill=rec, suspend_tick=tick,
                  d2h=None, h2d=None, bufs=None)
        self._ht_queue.append(req)
        if not rec.spilled:
            return                               # no content to move
        src = np.asarray([phys[j] for j in rec.spilled])
        # eager gather (compute thread): forces the read BEFORE the
        # next donated dispatch can recycle the cache buffers
        snaps = {name: np.asarray(self.scope.get(name)[src])
                 for name in self.cache_names}
        bufs, total = {}, 0
        for name, snap in snaps.items():
            buf = self._ht_pool.alloc(snap.shape, snap.dtype, "kv")
            bufs[name] = buf
            total += buf.nbytes

        def _spill(snaps=snaps, bufs=bufs):
            for name, snap in snaps.items():
                np.copyto(bufs[name].array, snap)

        st["bufs"] = bufs
        st["d2h"] = self._ht_stream.submit("d2h", _spill, total,
                                           tag=req.request_id)
        self.ht_d2h_bytes += total
        if not self._ht_per_block_bytes:
            self._ht_per_block_bytes = total // len(src)
        _offload.note_eviction(len(src))

    def _pick_victim(self, active: Dict[int, GenRequest],
                     tick: int) -> Optional[GenRequest]:
        """Rotation victim: the resident request with the MOST
        remaining work (it blocks the queue longest), provided it has
        been resident a full quantum (anti-thrash) and is not about to
        finish anyway. None = nobody qualifies, head keeps waiting."""
        quantum = self.host_tier.rotate_quantum
        best, best_rem = None, 0
        for req in active.values():
            st = self._ht_state[req.rid]
            if st["state"] != "resident":
                continue
            if tick - st.get("resume_tick", 0) < quantum:
                continue
            rem = self._remaining_ticks(req)
            if rem > max(best_rem, 2):
                best, best_rem = req, rem
        return best

    def _maybe_prefetch(self, active: Dict[int, GenRequest], tick: int):
        """Issue the head waiter's h2d staging `prefetch_distance`
        ticks ahead of its projected resume — the earlier of (a) the
        soonest resident finish and (b) the next rotation boundary.
        `offload.prefetch_issue_tick` is the ONE policy helper here and
        in `lint_program --offload` (linted == shipped)."""
        if not self._ht_queue:
            return
        head = self._ht_queue[0]
        st = self._ht_state[head.rid]
        if st["state"] != "spilled" or st["h2d"] is not None \
                or not st["spill"].spilled:
            return
        etas = [self._remaining_ticks(r) for r in active.values()
                if self._ht_state[r.rid]["state"] == "resident"]
        eta = min(etas) if etas else 0
        quantum = self.host_tier.rotate_quantum
        if quantum:
            eta = min(eta, max(quantum - (tick - st["suspend_tick"]), 0))
        if _offload.prefetch_issue_tick(
                tick + eta, self.host_tier.prefetch_distance) <= tick:
            self._stage_h2d(st)

    def _stage_h2d(self, st: Dict):
        """Stage the spilled content as device-placed arrays on the
        stream (on TPU this is the PCIe h2d; the block scatter at
        commit is an on-device copy). FIFO ordering makes the
        wait-for-d2h free: the spill job is ahead in the same queue."""
        bufs = st["bufs"]
        total = sum(b.nbytes for b in bufs.values())

        def _stage(bufs=bufs):
            import jax.numpy as jnp
            return {name: jnp.asarray(b.array)
                    for name, b in bufs.items()}

        st["h2d"] = self._ht_stream.submit("h2d", _stage, total,
                                           tag="prefetch")
        return st["h2d"]

    def _commit_h2d(self, moves: List[Tuple[int, int]], staged: Dict):
        """Scatter the staged block rows into the live cache arrays at
        their NEW physical ids, on the compute thread between ticks
        (single-writer), then mark the bound step's state stale so
        `_plain_tick` re-points it before dispatch."""
        dst = np.asarray([b for _, b in moves])
        for name, rows in staged.items():
            arr = self.scope.get(name)
            if hasattr(arr, "at"):
                arr = arr.at[dst].set(rows)
            else:
                arr = np.asarray(arr)
                arr[dst] = rows
            self.scope.set_var(name, arr)
        self._target_state_owner = "offload"

    # -- speculative-decoding hooks (serving/speculative.py) --------------
    def _build_verify_tick(self, gamma):
        from ..models import transformer
        d = self._builder_dims
        return transformer.transformer_lm_paged_spec_verify_tick(
            self.n_slots, gamma, n_blocks=self.n_blocks,
            block_size=self.block_size,
            blocks_per_req=self.blocks_per_req, vocab=d["vocab"],
            d_model=d["d_model"], d_inner=d["d_inner"],
            num_heads=d["num_heads"], num_layers=d["num_layers"],
            dropout=d["dropout"], packed=d["packed"],
            cache_prefix=self._cache_prefix, kv_quant=self.kv_quant)

    def _init_verify_feeds(self, g):
        f = super()._init_verify_feeds(g)
        f["spec_btab"] = np.zeros((self.n_slots, self.blocks_per_req),
                                  np.int64)
        f["spec_wblock"] = np.zeros((self.n_slots, g), np.int64)
        f["spec_woff"] = np.zeros((self.n_slots, g), np.int64)
        return f

    def _fill_verify_row(self, feeds, slot, req, g):
        super()._fill_verify_row(feeds, slot, req, g)
        blocks = req.table.blocks
        feeds["spec_btab"][slot, :len(blocks)] = blocks
        bs = self.block_size
        san = self.pager.sanitizer
        for j in range(g):
            lb, off = divmod(req.fed + j, bs)
            feeds["spec_wblock"][slot, j] = blocks[lb]
            feeds["spec_woff"][slot, j] = off
            if san is not None:
                # every speculative verify lane writes in place — each
                # target must be exclusively held (CoW contract)
                san.note_write(req.table, req.fed + j)

    def _spec_capable(self, req, g) -> bool:
        # the round's G writes must stay inside the request's block-table
        # span (host-side block lookup would index past the table)
        return (req.fed + g <= self.max_len
                and req.fed + g <= len(req.table.blocks) * self.block_size)

    def _spec_rollback(self, req, keep_len, written_len) -> int:
        return self.pager.rollback(req.table, keep_len, written_len)

    # -- limits / accounting ----------------------------------------------
    def _enforce_request_fits(self, prompt, max_new):
        enforce(len(prompt) + int(max_new) <= self.max_len,
                f"prompt({len(prompt)}) + max_new({max_new}) exceeds the "
                f"paged engine's per-request block-table span "
                f"blocks_per_req({self.blocks_per_req}) x block_size"
                f"({self.block_size}) = {self.max_len} tokens; pool "
                f"capacity ({self.n_blocks - 1} blocks) governs "
                f"ADMISSION (requests queue for blocks), not submission",
                exc=InvalidArgumentError)

    def _stamp_kv_watermarks(self, active: Dict[int, GenRequest]):
        # reserved = the whole pool (pinned at construction); used =
        # blocks actually allocated right now — live paging state, the
        # split the slot engine can only fake (its rows are always
        # reserved whole)
        per_block = self._kv_bytes_static / max(self.n_blocks, 1)
        _obs_memory.update_watermark("kv_cache_bytes",
                                     self._kv_bytes_static)
        _obs_memory.update_watermark("kv_cache_used_bytes",
                                     self.pager.pool.n_used * per_block)

    def _init_metrics(self):
        super()._init_metrics()
        r = self.metrics_registry
        pager = self.pager
        r.gauge("ptpu_engine_block_pool_blocks_used",
                "Allocated blocks in the paged KV pool.",
                fn=lambda: pager.pool.n_used)
        r.gauge("ptpu_engine_block_pool_blocks_free",
                "Free blocks in the paged KV pool.",
                fn=lambda: pager.pool.n_free)
        r.gauge("ptpu_engine_block_pool_occupancy",
                "Fraction of the paged KV pool's blocks allocated.",
                fn=lambda: (pager.pool.n_used
                            / max(pager.pool.n_blocks - 1, 1)))
        r.gauge("ptpu_engine_prefix_hit_rate",
                "Fraction of admitted requests that shared a cached "
                "prompt prefix.",
                fn=lambda: pager.stats()["prefix_hit_rate"])
        r.gauge("ptpu_engine_blocks_per_request",
                "Mean PRIVATE blocks allocated per admitted request "
                "(shared prefix blocks excluded — they are the saving).",
                fn=lambda: pager.stats()["blocks_per_request"])
        r.gauge("ptpu_engine_block_evictions_total",
                "Cached prefix blocks evicted (LRU, leaf-first) under "
                "pool pressure.", fn=lambda: pager.evictions)
        r.gauge("ptpu_engine_cow_copies_total",
                "Copy-on-write block copies at fork divergence points.",
                fn=lambda: pager.cow_copies)
        r.gauge("ptpu_engine_spec_rolled_back_blocks_total",
                "Block-table entries rolled back to fresh blocks after "
                "speculative verify rejected their whole span.",
                fn=lambda: pager.rolled_back_blocks)
        r.gauge("ptpu_engine_kv_quant_freed_bytes",
                "Bytes the int8 KV block pools save vs f32 pools at the "
                "same block count (0 with kv_quant off).",
                fn=lambda: self.kv_quant_freed_bytes)
        if self.host_tier is not None:
            _offload.offload_metrics()   # ptpu_offload_* (default reg)
            r.gauge("ptpu_engine_host_blocks_used",
                    "KV blocks resident on the host tier (spilled).",
                    fn=lambda: pager.host_blocks_used)
            r.gauge("ptpu_engine_suspended_requests",
                    "Admitted requests currently holding a tick slot "
                    "without device blocks (two-tier suspend).",
                    fn=lambda: len(self._ht_queue))
            r.gauge("ptpu_engine_host_prefetch_hit_rate",
                    "Fraction of host-tier resumes whose h2d prefetch "
                    "had already landed.",
                    fn=lambda: pager.stats()["host_tier"]
                    ["prefetch_hit_rate"])

    # -- device block ops -------------------------------------------------
    def _copy_block(self, src: int, dst: int):
        """Copy physical block src → dst across every layer's k/v pool
        (the CoW move). Host-driven between ticks — the tick program
        itself never writes a shared block, so this is the ONLY writer
        that can touch one, and it only reads it."""
        for name in self.cache_names:
            arr = self.scope.get(name)
            if hasattr(arr, "at"):               # jax array
                arr = arr.at[dst].set(arr[src])
            else:
                arr = np.asarray(arr)
                arr[dst] = arr[src]
            self.scope.set_var(name, arr)

    def stats(self) -> Dict:
        s = super().stats()
        s["pager"] = self.pager.stats()
        s["kv_quant"] = {"enabled": self.kv_quant,
                         "freed_bytes": self.kv_quant_freed_bytes}
        if self.host_tier is not None:
            # measured wire bytes (actual buffer sizes the stream moved)
            # next to the per-block figure the prediction side uses —
            # BENCH_OFFLOAD_r23.json asserts they reconcile EXACTLY
            s["offload"] = {
                "d2h_bytes": self.ht_d2h_bytes,
                "h2d_bytes": self.ht_h2d_bytes,
                "per_block_bytes": self._ht_per_block_bytes,
                "suspended": len(self._ht_queue),
            }
        return s


def paged_beam_search(engine: PagedKVEngine, prompt: Sequence[int],
                      max_new: int, beam_size: int,
                      eos_id: Optional[int] = None
                      ) -> List[Tuple[List[int], float]]:
    """Beam search through a PagedKVEngine's compiled tick, with the
    beams' common prefix held ONCE in the block pool.

    The prompt prefills a single hypothesis; the fork into `beam_size`
    beams shares every fully-written block by refcount and copy-on-
    writes the partial divergence block (`KVPager.fork`). Each decode
    tick runs all live beams as independent tick slots; the tick's
    top-k log-probs (engine built with `topk_k >= beam_size`) score the
    beam_size × k candidate extensions on the host, and every parent
    that survives in more than one child is forked again — CoW at the
    new divergence block. Beams that emit `eos_id` retire with their
    score frozen.

    Prefix sharing composes transparently: a cached prefix (from an
    earlier request, or a previous beam call with the same prompt)
    short-circuits the prefill exactly as in greedy serving, and the
    result is token-identical either way — shared blocks hold byte-
    identical K/V because compute is deterministic (pinned by
    tests/test_kv_pager.py).

    Returns [(tokens, cumulative log-prob)] sorted best-first,
    `beam_size` entries. The engine must be idle — beam decode owns
    every tick slot while it runs."""
    enforce(isinstance(engine, PagedKVEngine),
            "paged_beam_search needs a PagedKVEngine",
            exc=InvalidArgumentError)
    enforce(engine.topk_k >= beam_size,
            f"engine was built with topk_k={engine.topk_k}; beam_size="
            f"{beam_size} needs topk_k >= beam_size",
            exc=InvalidArgumentError)
    enforce(beam_size >= 1 and beam_size <= engine.n_slots,
            f"beam_size {beam_size} must fit the engine's "
            f"{engine.n_slots} tick slots", exc=InvalidArgumentError)
    enforce(engine.n_active == 0 and engine.n_pending == 0,
            "paged_beam_search needs an idle engine (it owns every "
            "tick slot)", exc=InvalidArgumentError)
    prompt = [int(t) for t in prompt]
    max_new = int(max_new)
    enforce(len(prompt) >= 1 and max_new >= 1,
            "need a non-empty prompt and max_new >= 1",
            exc=InvalidArgumentError)
    engine._enforce_request_fits(prompt, max_new)
    pager, bs, P = engine.pager, engine.block_size, len(prompt)
    need_len = min(P + max_new, engine.max_len)

    root = pager.try_admit(prompt, need_len)
    enforce(root is not None,
            "block pool exhausted (even after eviction) — cannot admit "
            "the beam root", exc=InvalidArgumentError)

    feeds = engine._feeds

    def _zero():
        for a in feeds.values():
            a[:] = 0

    def _tick(slots):
        """slots: {slot: (tok, pos, table)} — run one compiled tick,
        return (topk_logp [S,1,k], topk_ids [S,1,k]) as numpy."""
        _zero()
        san = pager.sanitizer
        for slot, (tok, pos, table) in slots.items():
            feeds["tick_tok"][slot, 0] = tok
            feeds["tick_pos"][slot, 0, 0] = float(pos)
            feeds["tick_btab"][slot, :len(table.blocks)] = table.blocks
            lb, off = divmod(pos, bs)
            feeds["tick_wblock"][slot] = table.blocks[lb]
            feeds["tick_woff"][slot] = off
            if san is not None:
                # beam writes ride the CoW contract too: each live
                # hypothesis must own its write block exclusively
                san.note_write(table, pos)
        out = engine._step.run(feeds)
        # run() re-pointed the main step's bound rw tuple at the live
        # cache arrays — a co-resident speculative verify step must
        # refresh before it next runs
        engine._target_state_owner = "main"
        engine.n_ticks += 1
        engine.last_tick_at = time.time()
        return np.asarray(out[1]), np.asarray(out[2])

    # -- prefill the root hypothesis through slot 0 (shared span skipped)
    logp = ids = None
    for pos in range(root.shared_len, P):
        logp, ids = _tick({0: (prompt[pos], pos, root)})
        if (pos + 1) % bs == 0:
            pager.note_block_filled(root, pos // bs, prompt)

    # -- fork the root into beam_size hypotheses (CoW at the partial
    #    block; with P % bs == 0 the fork is pure sharing, zero copies)
    beams = []
    for b in range(beam_size):
        table = pager.fork(root, P, engine._copy_block)
        tok = int(ids[0, 0, b])
        beams.append({"table": table, "tokens": [tok], "next_tok": tok,
                      "score": float(logp[0, 0, b]), "alive": True})
    pager.release(root)
    finished: List[Dict] = []
    for beam in beams:
        if eos_id is not None and beam["next_tok"] == eos_id:
            beam["alive"] = False
            finished.append(beam)
    beams = [b_ for b_ in beams if b_["alive"]]

    # -- decode: all live beams per tick, host-side candidate selection
    for g in range(1, max_new):
        if not beams:
            break
        slots = {i: (beam["next_tok"], P - 1 + g, beam["table"])
                 for i, beam in enumerate(beams)}
        logp, ids = _tick(slots)
        cands = []
        for i, beam in enumerate(beams):
            for j in range(beam_size):
                cands.append((beam["score"] + float(logp[i, 0, j]),
                              i, int(ids[i, 0, j])))
        cands.sort(key=lambda c: c[0], reverse=True)
        cands = cands[:len(beams)]
        # fork parents that survive in >1 child; retire the childless.
        # Forks run BEFORE any child's next write, so the parent's
        # blocks still hold exactly the shared history (written_len =
        # P + g positions).
        n_children = {}
        for _, i, _t in cands:
            n_children[i] = n_children.get(i, 0) + 1
        new_beams = []
        taken = {}
        for score, i, tok in cands:
            parent = beams[i]
            taken[i] = taken.get(i, 0) + 1
            if taken[i] < n_children[i]:
                table = pager.fork(parent["table"], P + g,
                                   engine._copy_block)
            else:
                table = parent["table"]      # last child inherits
            nb = {"table": table, "tokens": parent["tokens"] + [tok],
                  "next_tok": tok, "score": score, "alive": True}
            new_beams.append(nb)
        for i, beam in enumerate(beams):
            if i not in n_children:
                pager.release(beam["table"])
        beams = []
        for nb in new_beams:
            if eos_id is not None and nb["next_tok"] == eos_id:
                nb["alive"] = False
                finished.append(nb)
            else:
                beams.append(nb)

    finished.extend(beams)
    for beam in finished:
        if beam["table"].blocks:
            pager.release(beam["table"])
    _zero()
    finished.sort(key=lambda b_: b_["score"], reverse=True)
    return [(beam["tokens"], beam["score"])
            for beam in finished[:beam_size]]
