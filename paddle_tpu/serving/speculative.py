"""Speculative decoding through the paged, quantized serving stack:
draft-model propose + single-forward verify against block tables.

The decode tick is memory-bound: every emitted token pays one full read
of the target weights + KV cache. Speculative decoding amortizes that
read — a cheap DRAFT model proposes γ tokens autoregressively, then the
TARGET model scores all γ+1 window positions in ONE forward (the same
fused decode-attention chain, widened along the query axis), and the
longest accepted prefix commits. Greedy mode is TOKEN-IDENTICAL to
target-only decode by construction: a drafted token is accepted iff it
equals the target's argmax at that position, and the first mismatch is
replaced by the target's own output — exactly the token the plain tick
would have emitted. Sampling mode preserves the target distribution via
rejection sampling (`rejection_sample`).

How it rides the existing stack:

- The DRAFT is the same transformer architecture built by
  `transformer_lm_decode_tick(param_prefix="draft_")`: every weight
  lives under the reserved `draft_` name prefix (its own census
  category, `params_draft`), initialized by COPYING the target's
  weights (optionally truncated to `draft_layers` layers) before the
  target's quantize pass erases the f32 payloads, then quantized to
  `SpecConfig.draft` bits (int4 default halves the draft's weight
  reads). Draft KV is slot-resident on BOTH engines — the draft never
  pages.
- The VERIFY forward is a dedicated tick program per engine
  (`transformer_lm_spec_verify_tick` / `transformer_lm_paged_spec_
  verify_tick`) sharing the TARGET's caches and weights by name: γ+1
  query positions ride the query-row axis of the same fused
  decode-attention kernel (bit-identical to γ+1 sequential plain ticks
  — pinned by tests/test_speculative.py), writes land through the same
  `cache_write`/`paged_cache_write` ops, and the quantize pass's
  twin-program path rewrites it onto the SAME resident @qparam/@qscale
  payloads as the main tick.
- Both draft and verify are BOUND prepared steps (PreparedStep.bind):
  the pure-spec steady state dispatches zero per-call setup. The verify
  step and the plain tick share the target caches, so whichever ran
  last owns the donated buffers — `PreparedStep.refresh_state()`
  re-points the other before it runs (tracked by the engine's
  `_target_state_owner`; pure spec rounds never refresh).
- On the paged engine, a rejected tail's fully-dead blocks roll back
  through `KVPager.rollback` (release + fresh alloc; pool invariants
  `used + free == n_blocks - 1` and refcounts hold after every round —
  `BlockPool.check()` runs per round under PTPU_SPEC_POOL_CHECK=1 and
  always in the tests/bench).
- Prompt positions inside the verify window are teacher-forced (the
  "draft" is the prompt itself, always accepted): prefill advances γ+1
  positions per round — chunked prefill for free.

Observability: each round emits a `speculate` span (the γ+1 draft
ticks) and a `verify` span (the single target forward); acceptance-rate
/ draft-overhead / rolled-back-blocks gauges land in the engine
registry AND the process default registry (labeled by engine), and
`engine.stats()["speculative"]` — hence /healthz — carries the counters.
`GenRequest.phases(subphases=True)` splits the decode window into
spec_draft / spec_verify sub-phases.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ..core.enforce import InvalidArgumentError, enforce
from ..observability import tracing as _tracing

#: reserved name prefix for draft-model state: the census classifier
#: (framework/costs.state_category) maps `draft_*` weights — including
#: quantized `draft_*@qparam` payloads — to the `params_draft` category
DRAFT_PREFIX = "draft_"


@dataclass
class SpecConfig:
    """Speculative-decoding knobs (`ContinuousBatchingEngine(...,
    speculative=SpecConfig(...))`).

    gamma         draft tokens proposed per round; the verify window is
                  γ+1 positions wide
    draft         draft weight precision: "f32" | "int8" | "int4" —
                  int4/int8 quantize the draft's own weight copies
                  (PTPU_QUANT_PARAMS=0 kill switch serves f32 regardless)
    draft_layers  truncate the draft to its first N layers (None = full
                  depth — the honest-high-acceptance default)
    sampling      False = greedy (token-identical to target-only
                  decode); True = rejection sampling preserving the
                  target distribution (seeded, host-side)
    seed          the host RNG seed for sampling mode
    """

    gamma: int = 4
    draft: str = "int8"
    draft_layers: Optional[int] = None
    sampling: bool = False
    seed: int = 0

    def __post_init__(self):
        enforce(int(self.gamma) >= 1, "gamma must be >= 1",
                exc=InvalidArgumentError)
        enforce(self.draft in ("f32", "int8", "int4"),
                f"draft must be 'f32', 'int8' or 'int4', "
                f"got {self.draft!r}", exc=InvalidArgumentError)
        self.gamma = int(self.gamma)


def rejection_sample(p: np.ndarray, q: np.ndarray, draft_token: int,
                     rng: np.random.RandomState):
    """One speculative rejection-sampling step: accept `draft_token`
    (drawn from draft distribution q) with probability min(1,
    p[d]/q[d]); on rejection draw from the residual norm(max(0, p-q)).
    Returns (token, accepted). The emitted token is distributed EXACTLY
    as p regardless of q (Leviathan et al.'s lemma) — pinned by the
    fixed-seed distribution test in tests/test_speculative.py."""
    p = np.asarray(p, np.float64)
    q = np.asarray(q, np.float64)
    d = int(draft_token)
    if rng.random_sample() < min(1.0, p[d] / max(float(q[d]), 1e-30)):
        return d, True
    resid = np.maximum(p - q, 0.0)
    z = float(resid.sum())
    if z <= 0.0:
        # numerically p <= q everywhere yet the accept draw failed
        # (p ~= q with float rounding): the residual is empty — the
        # target distribution itself is the correct fallback
        resid, z = p, float(p.sum())
    return int(rng.choice(len(p), p=resid / z)), False


class SpeculativeDecoder:
    """The per-engine speculative-decoding driver: owns the draft and
    verify programs/steps and runs the propose → verify → commit →
    rollback round. Built in two phases bracketing the engine's own
    setup: `build_draft()` BEFORE the target quantize pass (it must copy
    f32 weights), `finalize()` after the main step is prepared+bound."""

    def __init__(self, engine, config):
        if config is True:
            config = SpecConfig()
        elif isinstance(config, dict):
            config = SpecConfig(**config)
        enforce(isinstance(config, SpecConfig),
                f"speculative must be a SpecConfig (or True / kwargs "
                f"dict), got {type(config).__name__}",
                exc=InvalidArgumentError)
        self.engine = engine
        self.cfg = config
        self.draft_layers = (int(config.draft_layers)
                            if config.draft_layers is not None
                            else int(engine._builder_dims["num_layers"]))
        enforce(1 <= self.draft_layers
                <= engine._builder_dims["num_layers"],
                f"draft_layers {self.draft_layers} out of range "
                f"[1, {engine._builder_dims['num_layers']}]",
                exc=InvalidArgumentError)
        # -- counters (stats() / gauges) --
        self.rounds = 0
        self.draft_ticks = 0
        self.verify_forwards = 0
        self.draft_proposed = 0      # drafted window tokens evaluated
        self.draft_accepted = 0      # ... of those, accepted
        self.draft_s = 0.0
        self.verify_s = 0.0
        self.rolled_back = 0         # paged: block-table entries redone
        self._pool_check = os.environ.get(
            "PTPU_SPEC_POOL_CHECK", "0") not in ("0", "")

    # -- construction -----------------------------------------------------
    def build_draft(self):
        """Build the draft tick program (weights under `draft_`), copy
        the target's f32 weights into the draft names, and quantize the
        draft program at `cfg.draft`. MUST run before the target
        program's own quantize pass — afterwards the f32 payloads are
        gone from the scope."""
        from ..core import flags as _flags
        from ..core import unique_name
        from ..framework.passes import get_pass
        from ..framework.program import Program, program_guard
        from ..framework.scope import Scope
        from ..models import transformer

        eng = self.engine
        d = eng._builder_dims
        self._draft_program, self._draft_startup = Program(), Program()
        with program_guard(self._draft_program, self._draft_startup), \
                unique_name.guard():
            outs = transformer.transformer_lm_decode_tick(
                n_slots=eng.n_slots, vocab=d["vocab"],
                max_len=eng.max_len, d_model=d["d_model"],
                d_inner=d["d_inner"], num_heads=d["num_heads"],
                num_layers=self.draft_layers, dropout=d["dropout"],
                packed=d["packed"],
                cache_prefix=eng._cache_prefix + "dr",
                param_prefix=DRAFT_PREFIX, emit_logp=True)
        self._draft_ids, self.draft_cache_names, self._draft_logp = outs
        # weight copy: draft_<w> <- <w> for every draft parameter whose
        # target twin is resident (trained or engine-initialized); the
        # rest (the draft's own slot caches) take the startup init. The
        # copy is BY REFERENCE — with an f32 draft over an f32 target
        # the two names share one device buffer until either side's
        # quantize pass erases its f32 name.
        tmp = Scope()
        eng._exe.run(self._draft_startup, scope=tmp)
        for name in tmp.local_var_names():
            if eng.scope.has_var(name):
                continue
            src = name[len(DRAFT_PREFIX):]
            if name.startswith(DRAFT_PREFIX) and eng.scope.has_var(src):
                eng.scope.set_var(name, eng.scope.get(src))
            else:
                eng.scope.set_var(name, tmp.get(name))
        if self.cfg.draft in ("int8", "int4") \
                and _flags.get_flag("quant_params"):
            get_pass("quantize_params_pass",
                     bits=8 if self.cfg.draft == "int8" else 4)(
                self._draft_program, eng.scope)

    def finalize(self):
        """Build + quantize the verify program (target weights/caches by
        name — the quantize pass's twin path reuses the resident
        payloads), then prepare and BIND both steps. Runs after the
        engine's main step is prepared+bound."""
        from ..core import unique_name
        from ..framework.passes import get_pass
        from ..framework.program import Program, program_guard
        from ..framework.scope import Scope

        eng = self.engine
        g = self.cfg.gamma + 1
        self._verify_program, self._verify_startup = Program(), Program()
        with program_guard(self._verify_program, self._verify_startup), \
                unique_name.guard():
            (self._verify_ids, self._verify_logp,
             self.verify_cache_names) = eng._build_verify_tick(
                self.cfg.gamma)
        # target caches/weights are already resident; copy only what the
        # verify startup would mint beyond them (none today — belt and
        # braces against future builder state)
        tmp = Scope()
        eng._exe.run(self._verify_startup, scope=tmp)
        for name in tmp.local_var_names():
            if eng.scope.has_var(name):
                continue
            if eng.scope.has_var(name + "@qparam"):
                # the f32 name was ERASED by the target quantize pass and
                # its payload lives on as @qparam/@qscale — reinstalling
                # the startup's fresh random init here would make the
                # verify quantize pass below re-quantize garbage OVER the
                # resident payloads (they're shared with the main tick)
                continue
            eng.scope.set_var(name, tmp.get(name))
        if eng.quant is not None:
            get_pass("quantize_params_pass",
                     bits=8 if eng.quant == "int8" else 4)(
                self._verify_program, eng.scope)
        self._draft_feeds = {
            "tick_tok": np.zeros((eng.n_slots, 1), np.int64),
            "tick_pos": np.zeros((eng.n_slots, 1, 1), np.float32)}
        self._verify_feeds = eng._init_verify_feeds(g)
        self._draft_step = eng._exe.prepare(
            self._draft_program, dict(self._draft_feeds),
            [self._draft_ids, self._draft_logp],
            eng.scope).bind(self._draft_feeds)
        self._verify_step = eng._exe.prepare(
            self._verify_program, dict(self._verify_feeds),
            [self._verify_ids, self._verify_logp],
            eng.scope).bind(self._verify_feeds)
        self._rng = np.random.RandomState(self.cfg.seed)
        self._windows = np.zeros((eng.n_slots, g), np.int64)
        self._from_draft = np.zeros((eng.n_slots, g), bool)
        self._register_metrics()

    def _register_metrics(self):
        from ..observability.metrics import default_registry, get_or_create
        eng = self.engine
        specs = (
            ("ptpu_engine_spec_acceptance_rate",
             "Accepted draft tokens over evaluated draft proposals.",
             self.acceptance_rate),
            ("ptpu_engine_spec_draft_overhead",
             "Draft-phase share of speculative round wall time.",
             self.draft_overhead),
            ("ptpu_engine_spec_tokens_per_target_forward",
             "Tokens emitted per target forward (verify + plain ticks) "
             "— the speculative amortization headline.",
             lambda: (eng.tokens_out / max(eng.target_forwards, 1))),
            ("ptpu_engine_spec_rolled_back_blocks",
             "Paged-KV block-table entries rolled back after verify "
             "rejected their whole span (0 on the slot engine).",
             lambda: self.rolled_back),
        )
        for name, help_, fn in specs:
            get_or_create(eng.metrics_registry, "gauge", name, help_,
                          fn=fn)
            # the process default registry carries the same gauges
            # labeled per engine, so /metrics scrapes and /healthz see
            # them without reaching into the engine registry
            get_or_create(default_registry(), "gauge", name, help_,
                          labels={"engine": eng._cache_prefix}, fn=fn)

    # -- telemetry --------------------------------------------------------
    def acceptance_rate(self) -> float:
        return (self.draft_accepted / self.draft_proposed
                if self.draft_proposed else 0.0)

    def draft_overhead(self) -> float:
        total = self.draft_s + self.verify_s
        return self.draft_s / total if total else 0.0

    def draft_param_bytes(self) -> int:
        """Resident bytes of the draft model's weight state — the
        `params_draft` census category, measured from the actual scope
        arrays (the figure the r17 ledger identity reconciles)."""
        from ..framework.costs import state_category
        from ..observability.memory import per_device_bytes
        eng = self.engine
        seen, total = set(), 0
        for b in self._draft_program.blocks:
            for name, v in b.vars.items():
                if name in seen or not v.persistable \
                        or not eng.scope.has_var(name):
                    continue
                seen.add(name)
                if state_category(v, name) == "params_draft":
                    total += int(per_device_bytes(eng.scope.get(name)))
        return total

    def stats(self) -> Dict:
        return {
            "gamma": self.cfg.gamma,
            "draft": self.cfg.draft,
            "draft_layers": self.draft_layers,
            "sampling": self.cfg.sampling,
            "rounds": self.rounds,
            "draft_ticks": self.draft_ticks,
            "verify_forwards": self.verify_forwards,
            "draft_proposed": self.draft_proposed,
            "draft_accepted": self.draft_accepted,
            "acceptance_rate": self.acceptance_rate(),
            "draft_overhead": self.draft_overhead(),
            "rolled_back_blocks": self.rolled_back,
            "draft_param_bytes": self.draft_param_bytes(),
        }

    # -- the round --------------------------------------------------------
    def round(self, active: Dict[int, "GenRequest"]) -> List:
        """One speculative round over `active` (slot → request, every
        one spec-capable): γ+1 draft ticks build the token window, one
        verify forward scores it, the commit walk advances each request
        through its accepted prefix (sharing `_advance_slot` with the
        plain tick — identical phase/finish semantics), and the paged
        engine rolls back fully-rejected blocks. Returns the requests
        that finished."""
        eng = self.engine
        cfg = self.cfg
        gamma = cfg.gamma
        g = gamma + 1
        windows, from_draft = self._windows, self._from_draft
        windows[:] = 0
        from_draft[:] = False
        draft_logp = [None] * g if cfg.sampling else None
        dtok = self._draft_feeds["tick_tok"]
        dpos = self._draft_feeds["tick_pos"]

        t0 = time.perf_counter()
        with _tracing.span("speculate", "engine/speculate",
                           active=len(active), gamma=gamma):
            for slot, req in active.items():
                windows[slot, 0] = req.next_tok
            for j in range(g):
                dtok[:] = 0
                dpos[:] = 0.0
                for slot, req in active.items():
                    dtok[slot, 0] = windows[slot, j]
                    dpos[slot, 0, 0] = float(req.fed + j)
                fetches = self._draft_step.run_bound()
                self.draft_ticks += 1
                if j == gamma:
                    # the last tick exists to write the draft cache at
                    # position fed+γ (a full acceptance starts the next
                    # round one past it); its proposal is unused
                    break
                ids = np.asarray(fetches[0])
                logp = np.asarray(fetches[1]) if cfg.sampling else None
                for slot, req in active.items():
                    nxt = req.fed + j + 1
                    if nxt < len(req.prompt):
                        # teacher-forced: the window token IS the prompt
                        windows[slot, j + 1] = req.prompt[nxt]
                        continue
                    if cfg.sampling:
                        q = np.exp(logp[slot, 0].astype(np.float64))
                        q /= q.sum()
                        tok = int(self._rng.choice(len(q), p=q))
                    else:
                        tok = int(ids[slot, 0])
                    windows[slot, j + 1] = tok
                    from_draft[slot, j + 1] = True
                if cfg.sampling:
                    draft_logp[j + 1] = logp
        td = time.perf_counter()
        self.draft_s += td - t0

        with _tracing.span("verify", "engine/verify",
                           active=len(active), width=g):
            vf = self._verify_feeds
            for a in vf.values():
                a[:] = 0
            vf["spec_tok"][:] = windows
            for slot, req in active.items():
                eng._fill_verify_row(vf, slot, req, g)
            if eng._target_state_owner != "verify":
                self._verify_step.refresh_state()
                eng._target_state_owner = "verify"
            fetches = self._verify_step.run_bound()
            self.verify_forwards += 1
            eng.target_forwards += 1
            ids = np.asarray(fetches[0])                    # [S, G]
            vlogp = (np.asarray(fetches[1])                 # [S, G, V]
                     if cfg.sampling else None)
        tv = time.perf_counter()
        self.verify_s += tv - td
        self.rounds += 1

        # -- commit walk per slot -----------------------------------------
        finished = []
        for slot, req in active.items():
            k0 = req.fed
            req.spec_draft_s += td - t0
            req.spec_verify_s += tv - td
            fin = False
            for i in range(g):
                if req.fed < len(req.prompt) - 1:
                    # prompt position: teacher-forced, always advances
                    # (the plain tick ignores the model output here too)
                    fin = eng._advance_slot(req, int(ids[slot, i]))
                    if fin:
                        break
                    continue
                # generated position: emit + decide continuation
                accept_next = False
                if not cfg.sampling:
                    emitted = int(ids[slot, i])
                    if i < gamma:
                        accept_next = int(windows[slot, i + 1]) == emitted
                elif i < gamma:
                    p = np.exp(vlogp[slot, i].astype(np.float64))
                    p /= p.sum()
                    q = np.exp(draft_logp[i + 1][slot, 0]
                               .astype(np.float64))
                    q /= q.sum()
                    emitted, accept_next = rejection_sample(
                        p, q, int(windows[slot, i + 1]), self._rng)
                else:
                    p = np.exp(vlogp[slot, gamma].astype(np.float64))
                    p /= p.sum()
                    emitted = int(self._rng.choice(len(p), p=p))
                if i < gamma and from_draft[slot, i + 1]:
                    self.draft_proposed += 1
                    if accept_next:
                        self.draft_accepted += 1
                fin = eng._advance_slot(req, emitted)
                if fin or not accept_next:
                    break
            if fin:
                finished.append(req)
            elif req.fed < k0 + g:
                # rejected tail [fed, k0+g): fully-dead blocks roll back
                # (paged; the slot engine's stale rows are masked and
                # overwritten before exposure — rollback is a no-op)
                self.rolled_back += eng._spec_rollback(req, req.fed,
                                                       k0 + g)
        if self._pool_check and hasattr(eng, "pager"):
            eng.pager.pool.check()
        san = getattr(getattr(eng, "pager", None), "sanitizer", None)
        if san is not None:
            # shadow-state census after every round: rollback remapped
            # blocks and the accept path advanced write frontiers — the
            # full ownership invariants must hold at the boundary
            san.verify_full("speculative-round")
        return finished
