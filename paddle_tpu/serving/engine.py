"""Continuous-batching serving engine over the fused decode path.

≙ reference inference/api/api_impl.cc:126 — the serving hot loop as a
first-class perf surface — extended with the scheduling idea the reference
era didn't have: requests of different lengths share ONE compiled decode
program through a slot-indexed KV cache, so a new request joins the
in-flight batch the tick a slot frees instead of waiting for a static
batch to drain.

The pieces:

- `transformer_lm_decode_tick` (models/transformer.py) — one decode tick
  over persistable [S,1,nh,T,dh] slot caches with PER-SLOT positions
  (`cache_write(batch_axis=0)`, closing the uniform-`Pos` limitation for
  real), compiled once; fuse_decode_attention_pass rewrites its attention
  chains into the r06 fused decode kernel.
- `SlotAllocator` — free-list over the S cache rows; alloc on admission,
  free on completion. A reused slot needs NO cache reset: the per-slot
  mask exposes only positions <= the slot's own pos, and prefill rewrites
  rows 0..P-1 before they are ever exposed (asserted in
  tests/test_serving_engine.py).
- `ContinuousBatchingEngine` — request queue + scheduler + tick loop.
  Prefill is teacher-forced through the same tick program (the fed token
  is the next prompt token until the prompt is consumed, then the slot's
  previously sampled token), so one executable serves every mixture of
  request phases. Dispatch rides `Executor.prepare` — the per-call
  validation/signature-hash overhead is off the tick path.
- `EngineServer`/`EngineClient` — generation RPC over the serving.py v2
  transport (vectored frames, batched writes): the engine thread ticks
  while reader/writer threads move bytes, so decode and socket I/O
  overlap; completions landing on the same tick go out as one vectored
  send.

Scheduling policies (the A/B in tools/bench_serve.py):

- "continuous": admit whenever a slot is free — the engine's point.
- "static": admit only when ALL slots are free (form a batch, run it to
  full completion, drain, repeat) — the padded static-batch baseline.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..core.enforce import InvalidArgumentError, enforce
from ..observability import memory as _obs_memory
from ..observability import metrics as _obs_metrics
from ..observability import tracing as _tracing

# atomic in CPython: concurrent engine construction must not mint the
# same cache namespace (aliased slot caches in a shared scope)
_ENGINE_SEQ = __import__("itertools").count(1)


class SlotAllocator:
    """Free-list allocator over the decode batch's S cache rows."""

    def __init__(self, n_slots: int):
        enforce(n_slots >= 1, "need at least one slot",
                exc=InvalidArgumentError)
        self.n_slots = n_slots
        self._free = list(range(n_slots - 1, -1, -1))
        self._used = set()

    def alloc(self) -> Optional[int]:
        if not self._free:
            return None
        s = self._free.pop()
        self._used.add(s)
        return s

    def free(self, slot: int):
        enforce(slot in self._used, f"slot {slot} not allocated",
                exc=InvalidArgumentError)
        self._used.remove(slot)
        self._free.append(slot)

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_used(self) -> int:
        return len(self._used)


class GenRequest:
    """One generation request moving through the engine.

    Besides the wall-clock fields (`submitted_at`/`first_token_at`/
    `done_at`, kept for API compatibility), every lifecycle boundary is
    also stamped on the perf_counter clock — the monotonic timeline the
    trace ring uses — so the request's latency DECOMPOSES conservatively:

        queue_wait = admitted - submitted       (waiting for a slot)
        prefill    = first_token - admitted     (prompt ticks, TTFT part)
        decode     = done - first_token         (sampled-token ticks)
        transport  = sent - done                (completion frame on the
                                                 wire; 0 without a server)

    The four phases partition [submitted, sent] exactly — their sum IS
    the end-to-end latency (BENCH_REQTRACE's 5% acceptance bar is float
    noise headroom, not slack in the definition). `request_id` threads
    from EngineClient through admission, every tick's span attrs, and
    the completion frame."""

    __slots__ = ("rid", "request_id", "prompt", "max_new", "eos_id",
                 "tokens", "slot", "fed", "next_tok", "submitted_at",
                 "first_token_at", "done_at", "on_done", "_event",
                 "submitted_pc", "admitted_at", "admitted_pc",
                 "first_token_pc", "done_pc", "sent_at", "sent_pc",
                 "defer_transport", "table", "shared_len",
                 "spec_draft_s", "spec_verify_s")

    def __init__(self, rid, prompt, max_new, eos_id=None, on_done=None,
                 request_id: Optional[str] = None,
                 defer_transport: bool = False):
        self.rid = rid
        self.request_id = str(request_id) if request_id is not None \
            else f"req-{rid}"
        self.prompt = [int(t) for t in prompt]
        self.max_new = int(max_new)
        self.eos_id = eos_id
        self.tokens: List[int] = []
        self.slot: Optional[int] = None
        self.fed = 0                       # positions consumed so far
        self.next_tok = self.prompt[0]     # token the next tick feeds
        self.submitted_at = time.time()
        self.submitted_pc = time.perf_counter()
        self.admitted_at: Optional[float] = None
        self.admitted_pc: Optional[float] = None
        self.first_token_at: Optional[float] = None
        self.first_token_pc: Optional[float] = None
        self.done_at: Optional[float] = None
        self.done_pc: Optional[float] = None
        self.sent_at: Optional[float] = None
        self.sent_pc: Optional[float] = None
        self.on_done = on_done
        #: paged-KV engine state: the request's BlockTable, and how many
        #: leading prompt positions were satisfied from the prefix cache
        #: (prefill starts at `shared_len` instead of 0). None/0 on the
        #: slot engine.
        self.table = None
        self.shared_len = 0
        #: speculative-decoding sub-phase accumulators: wall seconds this
        #: request spent inside `speculate` (draft ticks) and `verify`
        #: (target forward) rounds — SUB-phases of prefill+decode, not a
        #: fifth/sixth partition member (phases(subphases=True))
        self.spec_draft_s = 0.0
        self.spec_verify_s = 0.0
        #: True when a server OWNS the transport phase (it will call
        #: engine.report_sent once the completion frame is on the wire
        #: — or immediately if the frame cannot be delivered); False =
        #: no wire, transport/e2e close at completion
        self.defer_transport = bool(defer_transport)
        self._event = threading.Event()

    @property
    def done(self) -> bool:
        return self.done_at is not None

    @property
    def latency_s(self) -> Optional[float]:
        return (self.done_at - self.submitted_at) if self.done else None

    def phases(self, subphases: bool = False) -> Optional[Dict[str, float]]:
        """{queue_wait, prefill, decode, transport} seconds (transport 0
        until/unless a server reports the completion frame sent); None
        before completion. The four phases always partition
        [submitted, sent] exactly. With `subphases=True`, a request
        served speculatively additionally reports `spec_draft` and
        `spec_verify` — SUB-phases of the prefill+decode window (their
        sum is bounded by prefill+decode, not added to the partition)."""
        if self.done_pc is None:
            return None
        first = self.first_token_pc if self.first_token_pc is not None \
            else self.done_pc
        ph = {
            "queue_wait": self.admitted_pc - self.submitted_pc,
            "prefill": first - self.admitted_pc,
            "decode": self.done_pc - first,
            "transport": ((self.sent_pc - self.done_pc)
                          if self.sent_pc is not None else 0.0),
        }
        if subphases:
            ph["spec_draft"] = self.spec_draft_s
            ph["spec_verify"] = self.spec_verify_s
        return ph

    def e2e_s(self) -> Optional[float]:
        """Measured end-to-end latency on the perf_counter clock:
        submit → completion frame sent (→ completion when no server is
        involved). The number the phase decomposition must sum to."""
        if self.done_pc is None:
            return None
        end = self.sent_pc if self.sent_pc is not None else self.done_pc
        return end - self.submitted_pc

    def wait(self, timeout: Optional[float] = None) -> List[int]:
        if not self._event.wait(timeout):
            raise TimeoutError(f"request {self.rid} not done in {timeout}s")
        return self.tokens

    def _complete(self):
        self.done_at = time.time()
        self.done_pc = time.perf_counter()
        if self.on_done is not None:
            self.on_done(self)
        self._event.set()


class ContinuousBatchingEngine:
    """Slot-scheduled decode loop: one compiled tick, S independent
    sequences in flight, admission the tick a slot frees.

    Weights are shared BY NAME with a `transformer_lm` train graph (train
    or load into `scope` first, then hand the same scope here); absent
    parameters are initialized by this engine's own startup program, so a
    fresh engine also runs standalone (random weights — tests, benches).
    """

    def __init__(self, n_slots: int = 8, vocab: int = 32000,
                 max_len: int = 64, d_model: int = 512, d_inner: int = 2048,
                 num_heads: int = 8, num_layers: int = 6,
                 dropout: float = 0.0, packed: bool = False,
                 eos_id: Optional[int] = None, scope=None,
                 policy: str = "continuous",
                 cache_prefix: Optional[str] = None,
                 quant: Optional[str] = None,
                 speculative=None):
        from ..core import unique_name
        from ..framework.executor import Executor
        from ..framework.program import Program, program_guard
        from ..framework.scope import Scope, global_scope

        enforce(policy in ("continuous", "static"),
                f"unknown scheduling policy {policy!r}",
                exc=InvalidArgumentError)
        if cache_prefix is None:
            # per-engine cache namespace: two engines sharing one scope
            # (e.g. both over the same trained weights) must not alias
            # each other's slot caches — shapes differ with n_slots
            cache_prefix = f"srv{next(_ENGINE_SEQ)}"
        self.policy = policy
        self.n_slots = n_slots
        self.max_len = max_len
        self.eos_id = eos_id
        #: the model dims + cache namespace, kept for the auxiliary
        #: program builders (speculative draft/verify ticks must match
        #: the main tick's architecture and share its cache names)
        self._cache_prefix = cache_prefix
        self._builder_dims = dict(
            vocab=vocab, d_model=d_model, d_inner=d_inner,
            num_heads=num_heads, num_layers=num_layers, dropout=dropout,
            packed=packed)
        self._slots = SlotAllocator(n_slots)
        self._active: Dict[int, GenRequest] = {}      # slot -> request
        self._pending: "deque[GenRequest]" = deque()
        self._lock = threading.Lock()
        self._rid = 0

        self._program, self._startup = Program(), Program()
        with program_guard(self._program, self._startup), \
                unique_name.guard():
            self._build_tick_program(
                n_slots, vocab, max_len, d_model, d_inner, num_heads,
                num_layers, dropout, packed, cache_prefix)
        self.scope = scope or global_scope()
        self._exe = Executor()
        self._init_missing_vars(Scope)
        # speculative decoding (serving/speculative.py): the draft model
        # COPIES the target's f32 weights under the reserved `draft_`
        # prefix, so it must be built BEFORE the target quantize pass
        # erases the f32 payloads; its prepared steps bind in
        # `spec.finalize()` after the main step is bound below
        self.spec = None
        if speculative is not None and speculative is not False:
            from .speculative import SpeculativeDecoder
            self.spec = SpeculativeDecoder(self, speculative)
            self.spec.build_draft()
        # weight-only quantized serving (quant='int8'/'int4'): rewrite the
        # tick program's persistable f32 weights into block-scaled
        # (payload, scales) pairs BEFORE the step is prepared. The freed
        # f32 bytes (quant_freed_bytes) are KV headroom: at a fixed HBM
        # budget they buy extra BlockPool blocks on the paged engine
        # (tools/bench_qserve.py measures the admitted-concurrency win).
        # Kill switch PTPU_QUANT_PARAMS=0 serves f32 regardless of `quant`.
        enforce(quant in (None, "int8", "int4"),
                f"quant must be None, 'int8' or 'int4', got {quant!r}",
                exc=InvalidArgumentError)
        self.quant = None
        self.params_bytes_f32 = self._param_bytes()
        self.quant_freed_bytes = 0
        if quant is not None:
            from ..core import flags as _flags
            if _flags.get_flag("quant_params"):
                from ..framework.passes import get_pass
                get_pass("quantize_params_pass",
                         bits=8 if quant == "int8" else 4)(
                    self._program, self.scope)
                self.quant = quant
                self.params_bytes_quantized = self._param_bytes()
                self.quant_freed_bytes = (self.params_bytes_f32
                                          - self.params_bytes_quantized)
        self._feeds = self._init_tick_feeds()
        self._tok = self._feeds["tick_tok"]
        self._pos = self._feeds["tick_pos"]
        self._step = self._exe.prepare(
            self._program, dict(self._feeds), self._tick_fetches(),
            self.scope)
        # zero-dispatch steady state: the prepared step is BOUND to the
        # engine's in-place-mutated feed arrays — argument tuples are
        # built once here, never per tick (PreparedStep.bind)
        self._step.bind(self._feeds)
        # which bound step's held rw tuple points at the LIVE target
        # caches: "main" (the plain tick) or "verify" (the speculative
        # verify forward). The two share the donated cache buffers, so
        # whichever runs after the other refreshes first
        # (PreparedStep.refresh_state); pure steady states never refresh.
        self._target_state_owner = "main"
        # census counters (tools/bench_serve.py occupancy evidence)
        self.n_ticks = 0
        self.busy_slot_ticks = 0
        self.total_slot_ticks = 0
        self.tokens_out = 0
        #: TARGET-model forwards executed (plain ticks + verify
        #: forwards): the denominator of tokens-per-target-forward — the
        #: speculative amortization headline (tools/bench_spec.py)
        self.target_forwards = 0
        self._started_at = time.time()
        #: wall time of the last executed decode tick (None before the
        #: first) — /healthz reports its age as the liveness signal
        self.last_tick_at: Optional[float] = None
        #: completed requests, newest last (bounded) — the per-request
        #: latency decomposition record tools/bench_reqtrace.py reads
        self.completed_log: "deque[GenRequest]" = deque(maxlen=512)
        self._init_metrics()
        # the slot KV caches are persistable fixed-shape state: their
        # byte census is pinned at construction. Seed the process-wide
        # kv watermark (ptpu_memory_kv_cache_bytes) now so a scrape or a
        # dossier taken before the first tick already carries it; ticks
        # re-stamp it (two engines in one process: last writer wins the
        # `current`, the peak ratchets over both)
        self._kv_bytes_static = self._kv_cache_bytes()
        # per-token KV bytes across every layer cache: what ONE occupied
        # position costs — the unit of the used-vs-reserved split
        self._kv_bytes_per_token = (self._kv_bytes_static
                                    / max(n_slots * max_len, 1))
        self._stamp_kv_watermarks({})
        if self.spec is not None:
            # builds + quantizes the verify program (twin of the main
            # tick — same resident payloads), binds both spec steps,
            # registers the spec gauges
            self.spec.finalize()

    # -- tick-program construction (overridden by PagedKVEngine) ----------
    def _build_tick_program(self, n_slots, vocab, max_len, d_model,
                            d_inner, num_heads, num_layers, dropout,
                            packed, cache_prefix):
        """Build the compiled tick into the current default programs; must
        set `self._next_ids` (the [S,1] int64 fetch) and
        `self.cache_names` (the persistable KV state var names)."""
        self._next_ids, self.cache_names = \
            _decode_tick_builder(n_slots, vocab, max_len, d_model,
                                 d_inner, num_heads, num_layers,
                                 dropout, packed, cache_prefix)

    def _init_tick_feeds(self) -> Dict[str, np.ndarray]:
        """The per-tick feed arrays, reused across ticks (filled in place
        by `_fill_tick_feeds` — the decode loop allocates nothing)."""
        return {"tick_tok": np.zeros((self.n_slots, 1), np.int64),
                "tick_pos": np.zeros((self.n_slots, 1, 1), np.float32)}

    def _tick_fetches(self):
        return [self._next_ids]

    def _fill_tick_feeds(self, active: Dict[int, "GenRequest"]):
        tok, pos = self._tok, self._pos
        tok[:] = 0
        pos[:] = 0.0
        for slot, req in active.items():
            tok[slot, 0] = req.next_tok
            pos[slot, 0, 0] = float(req.fed)

    def _stamp_kv_watermarks(self, active: Dict[int, "GenRequest"]):
        """The used-vs-reserved split (ISSUE r20 satellite): reserved is
        the engine's whole KV footprint (slot engine: every slot's full
        max_len row, pinned at construction), used is the positions
        live requests actually occupy — the gap between the two gauges
        IS the per-slot reservation waste paging reclaims."""
        used = sum(min(r.fed, self.max_len) for r in active.values()) \
            * self._kv_bytes_per_token
        _obs_memory.update_watermark("kv_cache_bytes",
                                     self._kv_bytes_static)
        _obs_memory.update_watermark("kv_cache_used_bytes", used)

    def _init_metrics(self):
        """Per-engine MetricsRegistry (observability/metrics.py) — the
        serving telemetry EngineServer exposes over HTTP /metrics and the
        ROADMAP-item-3 load harness scrapes: tokens/s, queue depth, slot
        occupancy, tick-latency quantiles, KV-cache bytes."""
        r = self.metrics_registry = _obs_metrics.MetricsRegistry()
        self._m_tokens = r.counter(
            "ptpu_engine_tokens_total", "Tokens sampled by the engine.")
        self._m_ticks = r.counter(
            "ptpu_engine_ticks_total", "Decode ticks executed.")
        self._m_completed = r.counter(
            "ptpu_engine_requests_completed_total", "Completed requests.")
        r.gauge("ptpu_engine_queue_depth",
                "Requests waiting for a slot.", fn=lambda: self.n_pending)
        r.gauge("ptpu_engine_active_slots",
                "Slots carrying an in-flight request.",
                fn=lambda: self.n_active)
        r.gauge("ptpu_engine_slot_occupancy",
                "Fraction of slot-ticks that carried a request.",
                fn=self.occupancy)
        r.gauge("ptpu_engine_kv_cache_bytes",
                "Bytes held by the slot-indexed KV caches.",
                fn=self._kv_cache_bytes)
        r.gauge("ptpu_engine_tokens_per_second",
                "Tokens sampled per wall second since engine start.",
                fn=lambda: (self.tokens_out
                            / max(time.time() - self._started_at, 1e-9)))
        self._m_tick_latency = r.histogram(
            "ptpu_engine_tick_latency_seconds",
            "Wall latency of one decode tick.",
            buckets=(1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2,
                     2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5))
        self._m_dispatch = r.histogram(
            "ptpu_engine_dispatch_seconds",
            "Host-side dispatch share of one decode tick: feed fill + "
            "bound-call argument handling up to the async-dispatch "
            "return, excluding the realization barrier (device wait).",
            buckets=(1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3,
                     2.5e-3, 5e-3, 1e-2, 2.5e-2))
        for q, name in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            r.gauge(f"ptpu_engine_tick_latency_{name}_seconds",
                    f"{name} decode-tick latency (histogram estimate).",
                    fn=(lambda q=q:
                        self._m_tick_latency.quantile(q) or 0.0))
        # per-request latency decomposition: one labeled histogram
        # family, phase=queue_wait|prefill|decode|transport, plus the
        # end-to-end series the phases must sum to (BENCH_REQTRACE)
        req_buckets = (1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2,
                       2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                       10.0, 30.0)
        self._m_req_phase = {
            phase: r.histogram(
                "ptpu_request_latency_seconds",
                "Per-request latency decomposition by lifecycle phase.",
                labels={"phase": phase}, buckets=req_buckets)
            for phase in ("queue_wait", "prefill", "decode", "transport")}
        self._m_req_e2e = r.histogram(
            "ptpu_request_e2e_seconds",
            "End-to-end request latency (submit -> completion frame "
            "sent; -> completion when no server is attached).",
            buckets=req_buckets)

    def _param_bytes(self) -> int:
        """Resident bytes of the tick program's weight state (census
        categories params + params_quantized) — the before/after pair of
        the weight-only quantization claim."""
        from ..framework.costs import state_category
        seen, total = set(), 0
        for b in self._program.blocks:
            for name, v in b.vars.items():
                if name in seen or not v.persistable \
                        or not self.scope.has_var(name):
                    continue
                seen.add(name)
                if state_category(v, name) in ("params",
                                               "params_quantized"):
                    total += int(_obs_memory.per_device_bytes(
                        self.scope.get(name)))
        return total

    def _kv_cache_bytes(self) -> int:
        total = 0
        for name in self.cache_names:
            if not self.scope.has_var(name):
                continue
            v = self.scope.get(name)
            if hasattr(v, "dtype") and hasattr(v, "shape"):
                total += int(np.prod(v.shape)) * np.dtype(v.dtype).itemsize
        return total

    def _init_missing_vars(self, Scope):
        """Run the startup program into a throwaway scope and copy ONLY
        the vars the serving scope lacks: trained weights already present
        (shared by name) must not be re-randomized; caches and any
        untrained parameters get their init."""
        tmp = Scope()
        self._exe.run(self._startup, scope=tmp)
        for name in tmp.local_var_names():
            if not self.scope.has_var(name):
                self.scope.set_var(name, tmp.get(name))

    # -- request intake ---------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new: int,
               eos_id: Optional[int] = "engine",
               on_done: Optional[Callable] = None,
               request_id: Optional[str] = None,
               defer_transport: bool = False) -> GenRequest:
        """Queue a generation request; returns the GenRequest handle
        (wait() for completion, or pass on_done — called on the ENGINE
        thread, keep it cheap). `request_id` is the caller's correlation
        id (EngineClient threads it through the RPC frame); it rides
        every span and the completion frame — auto-minted when absent."""
        enforce(len(prompt) >= 1, "prompt must not be empty",
                exc=InvalidArgumentError)
        self._enforce_request_fits(prompt, max_new)
        with self._lock:
            self._rid += 1
            req = GenRequest(self._rid, prompt, max_new,
                             self.eos_id if eos_id == "engine" else eos_id,
                             on_done, request_id=request_id,
                             defer_transport=defer_transport)
            self._pending.append(req)
        return req

    def _enforce_request_fits(self, prompt, max_new):
        """The per-request length limit, NAMED for what it actually is
        (ISSUE r20 satellite): on the slot engine every request owns one
        fixed [max_len] KV row, so the row width is the hard cap. The
        paged engine overrides this — there the cap is the block table's
        span; pool capacity governs admission, not submission."""
        enforce(len(prompt) + int(max_new) <= self.max_len,
                f"prompt({len(prompt)}) + max_new({max_new}) exceeds the "
                f"slot engine's per-slot KV row width max_len="
                f"{self.max_len} (each slot reserves one full-length "
                f"row; use PagedKVEngine for pool-capacity-bound "
                f"admission)", exc=InvalidArgumentError)

    # -- scheduler --------------------------------------------------------
    def _admit_request(self, req: GenRequest) -> bool:
        """Admission-time resource acquisition beyond the slot itself.
        Called under the engine lock with a slot guaranteed free; True
        admits, False leaves the request pending (head-of-line wait —
        FIFO admission must not starve a big request behind small ones).
        The paged engine acquires the request's block table here."""
        return True

    def _release_request(self, req: GenRequest):
        """Completion-side resource release (called under the engine
        lock, paired with `_admit_request`). The paged engine returns
        the request's blocks to the pool / prefix cache here."""

    def _note_position_written(self, req: GenRequest, pos: int):
        """One cache position of `req` was written by the tick that just
        ran. The paged engine uses this to mark prefix blocks filled
        (sharable) the moment their last row lands."""

    def _note_tick_writes(self, active: Dict[int, "GenRequest"]):
        """Pre-dispatch hook naming the cache positions the imminent
        tick will write. The paged engine's shadow-state sanitizer
        (`PTPU_KV_SANITIZE=1`) checks each one against the ownership
        model here — a write into a shared or freed block raises its
        named diagnostic BEFORE the scatter runs. Default: no-op (the
        slot engine's per-slot rows cannot alias)."""

    # -- speculative-decoding hooks (overridden by PagedKVEngine) ---------
    def _build_verify_tick(self, gamma):
        """Build the verify program (γ+1-wide window forward over the
        TARGET's caches and weights, shared by name) into the current
        default programs; returns (ids, logp, cache_names)."""
        from ..models import transformer
        d = self._builder_dims
        return transformer.transformer_lm_spec_verify_tick(
            n_slots=self.n_slots, gamma=gamma, vocab=d["vocab"],
            max_len=self.max_len, d_model=d["d_model"],
            d_inner=d["d_inner"], num_heads=d["num_heads"],
            num_layers=d["num_layers"], dropout=d["dropout"],
            packed=d["packed"], cache_prefix=self._cache_prefix)

    def _init_verify_feeds(self, g: int) -> Dict[str, np.ndarray]:
        """The verify forward's reusable feed arrays (g = γ+1)."""
        return {"spec_tok": np.zeros((self.n_slots, g), np.int64),
                "spec_pos": np.zeros((self.n_slots, 1, 1), np.float32)}

    def _fill_verify_row(self, feeds, slot: int, req: GenRequest,
                         g: int):
        """Fill slot `slot`'s verify-feed rows for a window starting at
        `req.fed` (spec_tok is filled batch-wide by the caller)."""
        feeds["spec_pos"][slot, 0, 0] = float(req.fed)

    def _spec_capable(self, req: GenRequest, g: int) -> bool:
        """Can `req` take a full γ+1 window without overrunning its KV
        span? A single ineligible slot degrades the whole step to one
        plain tick (mixed windows aren't worth a second compiled
        shape)."""
        return req.fed + g <= self.max_len

    def _spec_rollback(self, req: GenRequest, keep_len: int,
                       written_len: int) -> int:
        """Positions [keep_len, written_len) of `req` were written by a
        verify forward but rejected. Slot engine: a no-op — the stale
        rows sit above the slot's position mask and are rewritten before
        they are ever exposed (the same write-before-expose argument as
        slot reuse). The paged engine rolls fully-dead blocks back
        through the pager. Returns the number of blocks rolled back."""
        return 0

    def _admit(self):
        admitted = []
        with _tracing.span("admission", "engine/admit",
                           pending=len(self._pending)), self._lock:
            if self.policy == "static" and (self._active
                                            or not self._pending):
                return
            while self._pending:
                if self.policy == "static" and \
                        self._slots.n_free == 0:
                    break
                if self.policy == "continuous" and \
                        self._slots.n_free == 0:
                    break
                if not self._admit_request(self._pending[0]):
                    break
                slot = self._slots.alloc()
                req = self._pending.popleft()
                req.slot = slot
                req.admitted_at = time.time()
                req.admitted_pc = time.perf_counter()
                self._active[slot] = req
                admitted.append(req)
        for req in admitted:
            # the queue-wait phase becomes a first-class span the moment
            # it ends (slot assignment) — retroactive, exact boundaries
            _tracing.record_span(
                "request", "request/queue_wait", req.submitted_pc,
                req.admitted_pc, request_id=req.request_id,
                slot=req.slot)
            self._m_req_phase["queue_wait"].observe(
                req.admitted_pc - req.submitted_pc)

    @property
    def n_active(self) -> int:
        with self._lock:
            return len(self._active)

    @property
    def n_pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def _advance_slot(self, req: GenRequest, out_id: int) -> bool:
        """Advance `req` one position with the model's output `out_id`
        for that position — the per-slot commit shared by the plain tick
        and every speculative verify position (identical phase stamps and
        finish semantics by construction). Returns True when the request
        just finished (max_new / eos / out of room)."""
        k = req.fed                    # the position just consumed
        req.fed += 1
        self._note_position_written(req, k)
        if k < len(req.prompt) - 1:
            req.next_tok = req.prompt[k + 1]     # still prefilling
            return False
        t = int(out_id)                          # sampled next token
        if req.first_token_at is None:
            req.first_token_at = time.time()
            req.first_token_pc = time.perf_counter()
        req.tokens.append(t)
        self.tokens_out += 1
        self._m_tokens.inc()
        req.next_tok = t
        hit_eos = (req.eos_id is not None and t == req.eos_id)
        out_of_room = req.fed >= self.max_len
        return len(req.tokens) >= req.max_new or hit_eos or out_of_room

    def step(self) -> List[GenRequest]:
        """One decode step: admit, run, collect. Returns the requests
        that COMPLETED on this step. A no-op (returns []) when nothing is
        active or pending. Without speculation (or when any active
        request is too close to its length cap to take a full window)
        this is one plain tick, recorded as a "tick" span and observed
        into the tick-latency histogram; with `speculative=` it is one
        speculative round (γ+1 draft ticks + one verify forward —
        `speculate`/`verify` spans) advancing every slot up to γ+1
        positions."""
        self._admit()
        with self._lock:
            active = dict(self._active)
        if not active:
            return []
        if self.spec is not None and all(
                self._spec_capable(r, self.spec.cfg.gamma + 1)
                for r in active.values()):
            finished = self.spec.round(active)
            self._m_ticks.inc()
            self.n_ticks += 1
            self.last_tick_at = time.time()
            self._stamp_kv_watermarks(active)
            self.busy_slot_ticks += len(active)
            self.total_slot_ticks += self.n_slots
        else:
            finished = self._plain_tick(active)
        if finished:
            # complete (firing on_done -> writer.offer) BEFORE dropping
            # the request from _active: a drain poll reading
            # n_active==0 must imply every completion frame is already
            # in its writer queue, or the drain could close the writer
            # ahead of the final frame and silently drop it
            for req in finished:
                req._complete()
            with self._lock:
                for req in finished:
                    del self._active[req.slot]
                    self._slots.free(req.slot)
                    self._release_request(req)
            self._m_completed.inc(len(finished))
            for req in finished:
                self._finalize_request(req)
        return finished

    def _pre_tick(self, active: Dict[int, "GenRequest"]
                  ) -> Dict[int, "GenRequest"]:
        """Scheduler hook run at the top of every plain tick, before the
        feeds fill: the two-tier offload engine (serving/kv_pager.py,
        `host_tier=`) resumes/suspends requests here — swapping KV
        blocks against the host tier between ticks — and returns the
        RESIDENT subset that actually ticks. Default: everything
        admitted is resident."""
        return active

    def _plain_tick(self, active: Dict[int, "GenRequest"]
                    ) -> List[GenRequest]:
        t0 = time.perf_counter()
        active = self._pre_tick(active)
        # the rid list is trace provenance only — don't build it per
        # tick when tracing is off (the decode loop is the hot path)
        span_attrs = {"active": len(active)}
        if _tracing.enabled():
            span_attrs["request_ids"] = [r.request_id
                                         for r in active.values()]
        with _tracing.span("tick", "engine/tick", **span_attrs):
            self._fill_tick_feeds(active)
            self._note_tick_writes(active)
            if self._target_state_owner != "main":
                # a speculative verify forward ran since the last plain
                # tick and owns the donated target-cache buffers —
                # re-point the bound step at the live arrays
                self._step.refresh_state()
                self._target_state_owner = "main"
            fetches = self._step.run_bound()   # zero-dispatch bound tick
            self.target_forwards += 1
            td = time.perf_counter()           # async dispatch returned
            ids = np.asarray(fetches[0])   # realization barrier: the next
            #                                tick's feed depends on it
        self._m_dispatch.observe(td - t0)
        if _tracing.enabled():
            # the host-dispatch share of the tick as a named phase
            # (PROBE_GAP_r07's `host_dispatch`, now first-class)
            _tracing.record_span("dispatch", "engine/dispatch", t0, td,
                                 active=len(active))
        self._m_tick_latency.observe(time.perf_counter() - t0)
        self._m_ticks.inc()
        self.n_ticks += 1
        self.last_tick_at = time.time()
        # re-stamp the kv watermarks so the live `current` reflects the
        # ENGINE that is actually ticking: reserved from the pinned
        # construction-time census, used from the positions live
        # requests occupy this tick (O(active))
        self._stamp_kv_watermarks(active)
        self.busy_slot_ticks += len(active)
        self.total_slot_ticks += self.n_slots
        finished = []
        for slot, req in active.items():
            if self._advance_slot(req, int(ids[slot, 0])):
                finished.append(req)
        return finished

    def _finalize_request(self, req: GenRequest):
        """Completion-side telemetry: the prefill/decode phase spans and
        histograms from the request's perf_counter stamps. The transport
        phase + end-to-end series land in `report_sent` when a server
        reports the completion frame on the wire; for a direct engine
        caller (no server → no wire) they are closed here with
        transport = 0, so the phase sums always match the e2e series."""
        first = req.first_token_pc if req.first_token_pc is not None \
            else req.done_pc
        _tracing.record_span("request", "request/prefill",
                             req.admitted_pc, first,
                             request_id=req.request_id, slot=req.slot,
                             prompt_len=len(req.prompt))
        _tracing.record_span("request", "request/decode", first,
                             req.done_pc, request_id=req.request_id,
                             slot=req.slot, new_tokens=len(req.tokens))
        ph = req.phases()
        self._m_req_phase["prefill"].observe(ph["prefill"])
        self._m_req_phase["decode"].observe(ph["decode"])
        self.completed_log.append(req)
        if not req.defer_transport:
            self._m_req_phase["transport"].observe(0.0)
            self._m_req_e2e.observe(req.e2e_s())

    def report_sent(self, req: GenRequest, sent_pc: float):
        """Server-side hook: the request's completion frame left the
        process at perf_counter time `sent_pc` (the _BatchingWriter
        on_sent callback). Closes the transport phase and the e2e
        series, and records the transport span."""
        req.sent_pc = float(sent_pc)
        req.sent_at = time.time()
        _tracing.record_span("request", "request/transport", req.done_pc,
                             req.sent_pc, request_id=req.request_id)
        self._m_req_phase["transport"].observe(req.sent_pc - req.done_pc)
        self._m_req_e2e.observe(req.e2e_s())

    def run_until_idle(self, max_ticks: Optional[int] = None
                       ) -> List[GenRequest]:
        """Tick until every pending/active request completed (or
        max_ticks); returns all completions in completion order."""
        done: List[GenRequest] = []
        ticks = 0
        while True:
            with self._lock:
                idle = not self._active and not self._pending
            if idle:
                return done
            done.extend(self.step())
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                return done

    def occupancy(self) -> float:
        """Fraction of slot-ticks that carried an active request —
        continuous batching's object of optimization."""
        return (self.busy_slot_ticks / self.total_slot_ticks
                if self.total_slot_ticks else 0.0)

    def stats(self) -> Dict:
        """Instantaneous engine state for /healthz: slot/queue shape,
        tick liveness, token throughput."""
        now = time.time()
        return {
            "n_slots": self.n_slots,
            "active": self.n_active,
            "pending": self.n_pending,
            "ticks": self.n_ticks,
            "tokens_out": self.tokens_out,
            "occupancy": self.occupancy(),
            "last_tick_age_s": ((now - self.last_tick_at)
                                if self.last_tick_at is not None
                                else None),
            "uptime_s": now - self._started_at,
            "target_forwards": self.target_forwards,
            "tokens_per_target_forward": (
                self.tokens_out / max(self.target_forwards, 1)),
            "speculative": (self.spec.stats()
                            if self.spec is not None else None),
        }


def _decode_tick_builder(n_slots, vocab, max_len, d_model, d_inner,
                         num_heads, num_layers, dropout, packed,
                         cache_prefix):
    from ..models import transformer
    return transformer.transformer_lm_decode_tick(
        n_slots=n_slots, vocab=vocab, max_len=max_len, d_model=d_model,
        d_inner=d_inner, num_heads=num_heads, num_layers=num_layers,
        dropout=dropout, packed=packed, cache_prefix=cache_prefix)


# ---------------------------------------------------------------------------
# Prometheus /metrics exposition + /healthz
# ---------------------------------------------------------------------------


class _MetricsHTTPServer:
    """Minimal threading HTTP listener serving GET /metrics (Prometheus
    text exposition 0.0.4 from one registry — Multi or plain) and, when
    a `health_fn` is given, GET /healthz as structured JSON (the control
    loop's signal: engine serving/draining state, last-tick age, pending
    checkpoints, supervisor restart count)."""

    def __init__(self, addr, registry, health_fn=None):
        import http.server
        import json as _json

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server contract)
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = registry.expose().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                    code = 200
                elif path == "/healthz" and health_fn is not None:
                    health = health_fn()
                    body = _json.dumps(health, default=str).encode()
                    ctype = "application/json"
                    # draining surfaces as 503: a load balancer must stop
                    # routing to a replica that stopped admitting
                    code = 200 if health.get("status") == "serving" \
                        else 503
                else:
                    self.send_error(404, "serving /metrics and /healthz")
                    return
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):   # scrapes must not spam stderr
                pass

        self._srv = http.server.ThreadingHTTPServer(addr, Handler)
        self._srv.daemon_threads = True
        self.server_address = self._srv.server_address

    def serve_forever(self):
        self._srv.serve_forever(poll_interval=0.1)

    def shutdown(self):
        self._srv.shutdown()

    def server_close(self):
        self._srv.server_close()


def scrape_metrics(host: str, port: int, timeout: float = 5.0) -> str:
    """One GET /metrics against an EngineServer's metrics address —
    what run_ci.sh and the tests use; production scrapers point Prometheus
    at the same URL."""
    import urllib.request
    with urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=timeout) as resp:
        return resp.read().decode()


def scrape_healthz(host: str, port: int, timeout: float = 5.0) -> Dict:
    """One GET /healthz (same listener as /metrics): the parsed JSON
    health document. A draining server answers 503 but still carries the
    body — this helper returns it either way."""
    import json as _json
    import urllib.error
    import urllib.request
    try:
        with urllib.request.urlopen(
                f"http://{host}:{port}/healthz", timeout=timeout) as resp:
            return _json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        if e.code == 503:   # draining: the body IS the health document
            return _json.loads(e.read().decode())
        raise


# ---------------------------------------------------------------------------
# generation RPC over the serving.py v2 transport
# ---------------------------------------------------------------------------


class EngineServer:
    """Serve a ContinuousBatchingEngine over TCP.

    Wire format is the serving.py framing with JSON-only frames:
      request   {"gen": {"prompt": [ids...], "max_new": n, "tag": any}}
      response  {"done": {"tag": any, "tokens": [ids...],
                          "latency_ms": float}}
    Responses are keyed by the client's `tag` (completion order is the
    ENGINE's order, not request order — short requests overtake long
    ones; that reordering is continuous batching working as designed).

    Threads: one engine thread ticks the decode loop; per connection, a
    reader admits requests and a writer flushes completions — completions
    landing on the same tick leave in one vectored send (serving.py
    `_sendall_vec`), so socket I/O and the decode tick overlap."""

    def __init__(self, engine: ContinuousBatchingEngine,
                 host: str = "127.0.0.1", port: int = 0,
                 metrics_port: Optional[int] = 0):
        import socket as _socket

        self.engine = engine
        self._sock = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        self._sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.address = self._sock.getsockname()
        self._stop = threading.Event()
        self._wake = threading.Event()     # submissions kick the engine
        self._draining = threading.Event()  # admit nothing new, finish rest
        self._threads: List[threading.Thread] = []
        self._conns: List = []
        self._writers: List = []
        self._lock = threading.Lock()
        self._prev_sigterm = None
        # Prometheus exposition + health: a small HTTP listener serving
        # GET /metrics and GET /healthz. A SEPARATE socket from the
        # generation RPC (that one speaks the serving.py frame protocol;
        # an HTTP GET on it would misparse as a frame header). The
        # scraped registry is the UNION of the engine's own registry and
        # the process-wide default registry, so one scrape sees serving,
        # checkpoint (ptpu_ckpt_*), and training (ptpu_train_*) series.
        # metrics_port=None disables; 0 picks an ephemeral port
        # (self.metrics_address after construction).
        self._http = None
        self.metrics_address = None
        if metrics_port is not None:
            # materialize the process-wide series before the first
            # scrape: ptpu_ckpt_* and ptpu_train_* register lazily, and
            # a scrape must see the families (at zero) even before the
            # first save/step touches them
            from ..parallel import elastic as _elastic
            from ..trainer import training_metrics as _training_metrics
            _elastic.metrics_registry()
            _training_metrics()
            _obs_memory.memory_metrics()   # ptpu_memory_* + ptpu_mfu
            self._http = _MetricsHTTPServer(
                (host, metrics_port),
                _obs_metrics.MultiRegistry(
                    [engine.metrics_registry,
                     _obs_metrics.default_registry()]),
                health_fn=self.health)
            self.metrics_address = self._http.server_address

    def health(self) -> Dict:
        """The /healthz document — the control-loop signal (ROADMAP
        3(d)): admission state (serving vs draining after SIGTERM),
        engine tick liveness, pending async checkpoint commits, and the
        supervising process's restart count (PTPU_SUPERVISOR_RESTARTS,
        set by trainer.Supervisor for its children)."""
        from ..parallel import elastic as _elastic
        restarts = os.environ.get("PTPU_SUPERVISOR_RESTARTS")
        return {
            "status": ("draining" if self._draining.is_set()
                       else "serving"),
            "engine": self.engine.stats(),
            "checkpoints": {
                "pending_async": _elastic.pending_async_count()},
            "supervisor": {
                "restarts": int(restarts) if restarts else 0},
            # the memory board (r17): per-channel current + high-water
            # bytes and the last MFU reading — the same board every
            # flight-recorder dossier embeds, so live probing and
            # post-mortems read one vocabulary
            "memory": _obs_memory.watermark_board(),
            "pid": os.getpid(),
            "ts": time.time(),
        }

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "EngineServer":
        t = threading.Thread(target=self._engine_loop, daemon=True)
        a = threading.Thread(target=self._accept_loop, daemon=True)
        self._threads += [t, a]
        t.start()
        a.start()
        if self._http is not None:
            h = threading.Thread(target=self._http.serve_forever,
                                 daemon=True)
            self._threads.append(h)
            h.start()
            self._http_started = True
        return self

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown (the SIGTERM path): stop admitting — the
        listener closes and new `gen` frames on live connections are
        answered with a draining error — finish every in-flight AND
        already-queued request, flush the per-connection writer threads
        so every completion frame reaches its client, then shut down.
        Returns True when the engine fully drained within `timeout`
        (False: timed out; shutdown still ran, undelivered work was
        dropped)."""
        # flag flips under the admission lock: every reader thread either
        # observed draining (and rejects) or completed its submit before
        # this point (and the idle wait below sees that request) — no
        # window where a request is admitted into a stopping engine
        with self._lock:
            self._draining.set()
        try:
            # closing the listener unblocks accept(); in-flight conns
            # stay open so completions can still go out
            self._sock.close()
        except OSError:
            pass
        deadline = None if timeout is None else time.time() + timeout
        drained = True
        while self.engine.n_active or self.engine.n_pending:
            self._wake.set()
            if deadline is not None and time.time() > deadline:
                drained = False
                break
            time.sleep(0.01)
        # flush writers BEFORE shutdown closes the sockets: close()
        # enqueues EOF and joins, so every queued completion frame is
        # vectored out first
        with self._lock:
            writers = list(self._writers)
        for w in writers:
            w.close()
        self.shutdown()
        return drained

    def install_sigterm_handler(self, exit_process: bool = True,
                                timeout: Optional[float] = None):
        """Wire SIGTERM to a graceful drain (main thread only — the
        signal module's contract). The handler returns immediately; a
        daemon thread performs the drain so the signal context never
        blocks, then — with exit_process — exits 0 (the k8s/preemption
        contract: SIGTERM means finish what you hold and leave
        cleanly)."""
        import signal as _signal

        def _handler(signum, frame):
            t = threading.Thread(target=self._drain_then_exit,
                                 args=(exit_process, timeout),
                                 daemon=True)
            t.start()

        self._prev_sigterm = _signal.signal(_signal.SIGTERM, _handler)
        return self

    def _drain_then_exit(self, exit_process: bool, timeout):
        try:
            self.drain(timeout=timeout)
            from ..parallel import elastic as _elastic
            # a co-resident elastic checkpoint writer must commit before
            # the process goes away (same drill as Trainer's
            # end-of-train flush)
            _elastic.wait_for_pending(timeout)
        except Exception as e:
            # a timed-out flush must not kill this thread BEFORE the
            # exit below: the SIGTERM disposition was replaced by our
            # handler, so skipping os._exit would leave a process that
            # ignores every further SIGTERM (undrainable zombie). The
            # exit-0 contract holds, but the failure must be visible —
            # operators need to tell a clean drain from a failed one
            from ..core import flags
            flags.vlog(0, "SIGTERM drain did not complete cleanly: "
                       "%s: %s (exiting anyway)", type(e).__name__, e)
        if exit_process:  # pragma: no cover - exits the interpreter
            os._exit(0)

    def shutdown(self):
        self._stop.set()
        self._wake.set()
        if self._http is not None:
            # socketserver's shutdown() blocks on an event only
            # serve_forever() ever sets — calling it when start() never
            # ran would hang forever; just close the listener then
            if getattr(self, "_http_started", False):
                self._http.shutdown()
            self._http.server_close()
        try:
            self._sock.close()
        except OSError:
            pass
        import socket as _socket
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            # shutdown BEFORE close: reader threads parked in recv are
            # not woken by closing the fd on Linux; shutdown makes recv
            # return 0 immediately (same drill as PredictorServer)
            try:
                c.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=10)

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.shutdown()

    # -- engine thread ----------------------------------------------------
    def _engine_loop(self):
        while not self._stop.is_set():
            if self.engine.n_active or self.engine.n_pending:
                self.engine.step()
            else:
                self._wake.wait(timeout=0.05)
                self._wake.clear()

    # -- I/O threads ------------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            import socket as _socket
            conn.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            with self._lock:
                self._conns.append(conn)
                self._threads.append(t)
            t.start()

    def _serve_conn(self, conn):
        from .transport import _BatchingWriter, _encode_msg, _recv_msg

        # shared with PredictorServer: bounded queue + vectored batch
        # drain. Completions use the NON-blocking offer(): the engine
        # thread ticks for every connection and must never stall on one
        # that stopped reading — a client ~64 unread frames behind is
        # evicted (connection closed), frames for a dead connection are
        # dropped.
        writer = _BatchingWriter(conn)
        with self._lock:
            self._writers.append(writer)

        def on_done(req, tag):
            ph = req.phases() or {}
            frame = _encode_msg({"done": {
                "tag": tag, "tokens": req.tokens,
                "request_id": req.request_id,
                "latency_ms": round(req.latency_s * 1e3, 3),
                "phases_ms": {k: round(v * 1e3, 3)
                              for k, v in ph.items()
                              if k != "transport"}}})
            # on_sent closes the transport phase: the writer thread
            # reports the perf_counter instant the vectored send
            # returned, and the engine observes transport + e2e. A
            # failed offer (dead writer / slow-consumer eviction) means
            # the frame will NEVER go out — close the series here so the
            # e2e count cannot lag the phase counts
            ok = writer.offer(frame, on_sent=(
                lambda ts, req=req: self.engine.report_sent(req, ts)))
            if not ok:
                self.engine.report_sent(req, time.perf_counter())

        try:
            while not self._stop.is_set():
                header, _ = _recv_msg(conn)
                if header is None or "gen" not in header:
                    break
                g = header["gen"]
                tag = g.get("tag")
                err = None
                admitted = False
                # check-and-submit under the admission lock (paired with
                # drain()'s locked flag flip): a submit can never slip in
                # after drain decided the engine is idle
                with self._lock:
                    if self._draining.is_set():
                        # graceful drain: in-flight work completes, but
                        # nothing new is admitted — the client gets an
                        # explicit rejection, never a silent drop
                        err = ("server draining (SIGTERM): not "
                               "admitting new requests")
                    else:
                        try:
                            self.engine.submit(
                                g["prompt"], g.get("max_new", 16),
                                on_done=(lambda req, tag=tag:
                                         on_done(req, tag)),
                                request_id=g.get("request_id"),
                                defer_transport=True)
                            admitted = True
                        except Exception as e:
                            err = f"{type(e).__name__}: {e}"
                if admitted:
                    self._wake.set()
                else:
                    # respond OUTSIDE the lock: it may block on writer
                    # backpressure
                    writer.respond(_encode_msg({"error": err,
                                                "tag": tag}))
        except (ConnectionError, OSError):
            pass
        finally:
            writer.close()
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)
                if writer in self._writers:
                    self._writers.remove(writer)


class EngineClient:
    """Client for EngineServer; supports pipelined generation requests."""

    def __init__(self, host: str, port: int):
        import socket as _socket

        self._sock = _socket.create_connection((host, port))
        self._sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        self._tag = 0

    def send_gen(self, prompt: Sequence[int], max_new: int = 16,
                 tag=None, request_id: Optional[str] = None):
        """`request_id` is the client's correlation id: it threads
        through admission, every decode tick's span attrs, the
        per-request latency decomposition, and comes back on the done
        frame — the end-to-end trace key across client/server/engine."""
        from .transport import _send_msg
        with self._lock:
            self._tag += 1
            tag = self._tag if tag is None else tag
            msg = {"gen": {"prompt": [int(t) for t in prompt],
                           "max_new": int(max_new), "tag": tag}}
            if request_id is not None:
                msg["gen"]["request_id"] = str(request_id)
            _send_msg(self._sock, msg)
        return tag

    def recv_done(self):
        """Next completion: (tag, tokens, latency_ms). Completion order is
        the engine's, not send order."""
        from .transport import _recv_msg
        header, _ = _recv_msg(self._sock)
        if header is None:
            raise ConnectionError("server closed the connection")
        if "error" in header:
            raise RuntimeError(f"server error: {header['error']}")
        d = header["done"]
        return d["tag"], d["tokens"], d["latency_ms"]

    def generate(self, prompt: Sequence[int], max_new: int = 16
                 ) -> List[int]:
        tag = self.send_gen(prompt, max_new)
        got_tag, tokens, _ = self.recv_done()
        if got_tag != tag:
            raise RuntimeError(
                f"unexpected completion tag {got_tag} (want {tag}); use "
                f"send_gen/recv_done for pipelined requests")
        return tokens

    def close(self):
        self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
