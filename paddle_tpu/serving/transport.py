"""Server-mode predictor: long-lived serve loop with concurrent requests.

≙ reference inference/api/api_impl.cc:126 (NativePaddlePredictor::Run — a
long-lived predictor object fielding many requests) and :170 (::Clone — one
shared-weights predictor per serving thread). The TPU translation:

- PredictorServer accepts TCP connections; each connection is served by a
  thread holding its own `predictor.clone()` (shared weights/executable
  cache source, private executor caches) — the clone-per-thread contract.
- The wire protocol is length-prefixed JSON + raw little-endian C-order
  tensor bytes, so clients in any language can speak it.
- A connection may pipeline requests (send several before reading): the
  per-connection thread answers strictly in order while OTHER connections
  run concurrently — XLA executions release the GIL, so concurrent
  requests genuinely overlap on device.

Transport (v2 — the round-5 serving link sat at 0.54–0.71 of what the
prefetcher sustained on the same link; the per-request turnaround below is
what closed it, BENCH_SERVE_r07.json):

- ZERO-COPY VECTORED FRAMING: a frame (length prefix + header + tensor
  payloads) goes out as ONE sendmsg syscall over memoryviews of the numpy
  buffers — no tobytes() copy, no per-part sendall round trip.
- BATCHED RESPONSE WRITES: each connection has a writer thread that drains
  every response ready at that moment and emits them as one vectored
  send, so a pipelined client's K responses pay one syscall, not K.
- DOUBLE-BUFFERED RECV: request payloads land in two pooled per-connection
  buffers via recv_into — the reader fills one while the worker still
  parses/stages the other; numpy views are taken zero-copy over the pool
  buffer and the buffer is recycled once the run consumed them.
- The decode/compute tick and socket I/O run on separate threads (reader,
  worker, writer), so neither blocks the other.

Protocol, per request:
    u32  header length
    JSON {"feeds": [{"name", "dtype", "shape"}...], "fetch": [...]? }
    raw tensor bytes for each feed, in header order
Response:
    u32  header length
    JSON {"outs": [{"name", "dtype", "shape"}...]}   (or {"error": msg})
    raw tensor bytes for each out
"""

from __future__ import annotations

import json
import queue as _queue
import socket
import struct
import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

# sendmsg takes at most IOV_MAX (commonly 1024) iovecs; stay well under
_IOV_CHUNK = 512


def _byte_views(parts):
    """Flat byte views (memoryview cast to 'B') over heterogeneous parts
    (bytes, bytearray, contiguous numpy arrays) — the zero-copy scatter
    list sendmsg consumes."""
    views = []
    for p in parts:
        mv = memoryview(p)
        if mv.ndim != 1 or mv.itemsize != 1:
            mv = mv.cast("B")
        if len(mv):
            views.append(mv)
    return views


def _sendall_vec(sock: socket.socket, parts):
    """Vectored sendall: the whole frame list in as few sendmsg syscalls
    as the kernel allows, advancing through partial sends."""
    views = _byte_views(parts)
    while views:
        try:
            sent = sock.sendmsg(views[:_IOV_CHUNK])
        except InterruptedError:
            continue
        while sent:
            if sent >= len(views[0]):
                sent -= len(views[0])
                views.pop(0)
            else:
                views[0] = views[0][sent:]
                sent = 0


def _encode_msg(header: dict, buffers=()):
    """Frame parts for one message (length prefix + JSON + payloads);
    payloads stay by-reference (zero-copy through sendmsg)."""
    raw = json.dumps(header).encode()
    return [struct.pack("<I", len(raw)), raw, *buffers]


def _send_msg(sock: socket.socket, header: dict, buffers=()):
    _sendall_vec(sock, _encode_msg(header, buffers))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        c = sock.recv(min(n, 1 << 20))
        if not c:
            raise ConnectionError("peer closed")
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)


def _recv_exact_into(sock: socket.socket, mv: memoryview):
    """recv_into the whole view (no intermediate bytes objects)."""
    while len(mv):
        n = sock.recv_into(mv, len(mv))
        if not n:
            raise ConnectionError("peer closed")
        mv = mv[n:]


class _RecvBufferPool:
    """N (default 2 — double buffering) reusable payload buffers: the
    reader fills one while the worker still parses/stages another;
    acquire blocks when all are in flight, which bounds per-connection
    buffer memory no matter how hard a client pipelines. Buffers grow to
    the largest payload seen and are reused at that size."""

    def __init__(self, n: int = 2):
        self._free: "_queue.Queue" = _queue.Queue()
        for _ in range(n):
            self._free.put(bytearray(0))

    def acquire(self, size: int, timeout=None) -> Optional[bytearray]:
        try:
            buf = self._free.get(timeout=timeout)
        except _queue.Empty:
            return None
        if len(buf) < size:
            buf = bytearray(size)
        return buf

    def release(self, buf: bytearray):
        self._free.put(buf)


_WRITER_EOF = object()


class _BatchingWriter:
    """Per-connection response writer thread: a BOUNDED queue drained so
    that every frame ready at wake-up leaves in ONE vectored send
    (batched response writes). Shared by PredictorServer and
    serving_engine.EngineServer — the drain/EOF/dead-flag subtleties
    live once.

    `respond` blocks under backpressure and gives up once the writer is
    gone (the PredictorServer worker's contract). `offer` never blocks:
    on a full queue it kills the connection (slow-consumer eviction —
    the engine's tick thread serves EVERY connection and must not stall
    on one that stopped reading)."""

    def __init__(self, conn, maxsize: int = 64):
        self._conn = conn
        self._q: "_queue.Queue" = _queue.Queue(maxsize=maxsize)
        self.dead = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    @staticmethod
    def _fire_sent(callbacks):
        """Run the batch's on_sent callbacks with ONE timestamp — the
        instant the vectored send returned, i.e. when the frames left
        the process (the transport-span boundary serving_engine's
        per-request latency decomposition records)."""
        if not callbacks:
            return
        import time as _time
        now = _time.perf_counter()
        for cb in callbacks:
            try:
                cb(now)
            except Exception:   # telemetry must not kill the writer
                pass

    def _loop(self):
        callbacks = []
        try:
            while True:
                item = self._q.get()
                if item is _WRITER_EOF:
                    return
                parts, cb = item
                parts = list(parts)
                callbacks = [cb] if cb is not None else []
                try:
                    while True:   # batch whatever else is ready NOW
                        nxt = self._q.get_nowait()
                        if nxt is _WRITER_EOF:
                            _sendall_vec(self._conn, parts)
                            self._fire_sent(callbacks)
                            callbacks = []
                            return
                        parts.extend(nxt[0])
                        if nxt[1] is not None:
                            callbacks.append(nxt[1])
                except _queue.Empty:
                    pass
                _sendall_vec(self._conn, parts)
                self._fire_sent(callbacks)
                callbacks = []
        except (ConnectionError, OSError):
            pass
        finally:
            self.dead.set()
            try:   # unblock producers stuck in put(); collect their
                # callbacks — these frames will never go out
                while True:
                    item = self._q.get_nowait()
                    if item is not _WRITER_EOF and item[1] is not None:
                        callbacks.append(item[1])
            except _queue.Empty:
                pass
            # close out EVERY un-fired on_sent (the in-flight batch a
            # ConnectionError interrupted + the drained queue): a dead
            # connection must not leave telemetry series lagging forever
            # — the callback gets the death instant as its timestamp
            self._fire_sent(callbacks)

    def respond(self, parts, on_sent=None) -> bool:
        """Blocking enqueue with backpressure; False once the writer is
        gone. `on_sent(perf_counter_ts)` fires after the frame's
        vectored send returned."""
        while not self.dead.is_set():
            try:
                self._q.put((parts, on_sent), timeout=0.2)
                return True
            except _queue.Full:
                continue
        return False

    def offer(self, parts, on_sent=None) -> bool:
        """Non-blocking enqueue. A full queue means the peer stopped
        reading ~maxsize frames ago: the connection is killed (the peer
        sees a disconnect, never a silent gap) and False returned."""
        if self.dead.is_set():
            return False
        try:
            self._q.put_nowait((parts, on_sent))
            return True
        except _queue.Full:
            self.dead.set()
            # shutdown BEFORE close: the writer thread may be blocked in
            # sendmsg on this socket, and closing the fd does not wake a
            # blocked send on Linux — shutdown does
            for fn in (lambda: self._conn.shutdown(socket.SHUT_RDWR),
                       self._conn.close):
                try:
                    fn()
                except OSError:
                    pass
            return False

    def close(self, join_timeout: float = 10.0):
        while not self.dead.is_set():
            try:
                self._q.put(_WRITER_EOF, timeout=0.2)
                break
            except _queue.Full:
                continue
        self._thread.join(timeout=join_timeout)


def _recv_msg(sock: socket.socket, pool: Optional[_RecvBufferPool] = None,
              dead=None):
    """Read one message. Without a pool, payloads are fresh bytes (the
    client path). With a pool (server reader), payloads are zero-copy
    memoryviews into a pooled buffer returned as the third element — the
    consumer must pool.release() it once the views are dead. `dead` (a
    callable) lets the pooled acquire give up when the consumer that
    would recycle buffers is gone."""
    try:
        hlen, = struct.unpack("<I", _recv_exact(sock, 4))
    except ConnectionError:
        return (None, None) if pool is None else (None, None, None)
    header = json.loads(_recv_exact(sock, hlen))
    specs = header.get("feeds", header.get("outs", []))
    sizes = [int(np.prod(spec["shape"])) * np.dtype(spec["dtype"]).itemsize
             for spec in specs]
    if pool is None:
        return header, [_recv_exact(sock, n) for n in sizes]
    buf = None
    while buf is None:
        buf = pool.acquire(sum(sizes), timeout=0.5)
        if buf is None and dead is not None and dead():
            raise ConnectionError("recv-buffer consumer gone")
    mv = memoryview(buf)
    buffers, off = [], 0
    for n in sizes:
        _recv_exact_into(sock, mv[off:off + n])
        buffers.append(mv[off:off + n])
        off += n
    return header, buffers, buf


class PredictorServer:
    """Serve a Predictor (or ExportedPredictor) over TCP.

    `predictor` needs .run(feed, fetch_names=None, return_numpy=True); if it
    has .clone(), every connection thread gets its own clone (≙ reference
    api_impl.cc:170), otherwise the single object is shared (safe for
    ExportedPredictor, whose call is stateless).
    """

    def __init__(self, predictor, host: str = "127.0.0.1", port: int = 0):
        self._base = predictor
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.address = self._sock.getsockname()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._lock = threading.Lock()
        self._accept_thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "PredictorServer":
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        return self

    def shutdown(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        # close live connections so threads blocked in recv() exit NOW
        # instead of eating the join timeout each
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.shutdown()

    # -- internals --------------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed by shutdown
            # a response frame is often tiny (header + small logits);
            # Nagle would hold it hostage to the previous frame's ACK and
            # a pipelined client sees 40 ms delayed-ACK stalls
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            with self._lock:
                self._conns.append(conn)
                self._threads = [x for x in self._threads if x.is_alive()]
                self._threads.append(t)
            t.start()

    def _serve_conn(self, conn: socket.socket):
        """Reader + worker + writer threads per connection. The reader
        ALWAYS drains incoming requests into a queue and the worker
        executes in order: with both roles on one thread, a client that
        pipelines faster than it reads would fill both TCP buffers and
        deadlock the pair in sendall (server not reading because it is
        writing). The queue is the explicit in-flight buffer. The writer
        decouples compute from socket writes the same way — the worker
        never blocks in send, and responses that pile up while one write
        is in flight go out together as a single vectored sendmsg
        (batched response writes). Request payloads land in a 2-buffer
        recv pool (double buffering): zero-copy numpy views feed the
        predictor and the buffer recycles when the run is done."""
        # per-thread context reuse: ONE clone for the connection's lifetime,
        # its executor caches warm across requests
        predictor = (self._base.clone() if hasattr(self._base, "clone")
                     else self._base)
        # bounded: past 32 queued requests the reader stops reading and
        # TCP backpressure reaches the client — a runaway pipeliner stalls
        # itself instead of growing server memory without limit. (The recv
        # pool bounds PAYLOAD memory at 2 buffers already; this bounds the
        # header/bookkeeping queue.)
        requests: "_queue.Queue" = _queue.Queue(maxsize=32)
        pool = _RecvBufferPool(2)
        _EOF = object()
        # set when the worker exits for ANY reason: a reader blocked in
        # put() or pool.acquire() must not wait forever for a consumer
        # that is gone (the worker also drains the queue on exit)
        worker_dead = threading.Event()
        writer = _BatchingWriter(conn)
        respond = writer.respond

        def work():
            while True:
                item = requests.get()
                if item is _EOF:
                    return
                header, buffers, buf = item
                try:
                    try:
                        feed = {}
                        for spec, raw in zip(header["feeds"], buffers):
                            # zero-copy view over the pooled recv buffer;
                            # predictor.run stages it to device (copies),
                            # after which the buffer can recycle
                            feed[spec["name"]] = np.frombuffer(
                                raw, dtype=np.dtype(spec["dtype"])).reshape(
                                    spec["shape"])
                        outs = predictor.run(
                            feed, fetch_names=header.get("fetch"),
                            return_numpy=True)
                        names = header.get("fetch") or getattr(
                            predictor, "fetch_names",
                            [f"out{i}" for i in range(len(outs))])
                        outs = [np.ascontiguousarray(o) for o in outs]
                        resp = {"outs": [
                            {"name": n, "dtype": str(o.dtype),
                             "shape": list(o.shape)}
                            for n, o in zip(names, outs)]}
                        # outs ride the frame by reference — the writer's
                        # sendmsg reads the numpy memory directly
                        if not respond(_encode_msg(resp, outs)):
                            return
                    except Exception as e:  # per-request error, keep going
                        if not respond(_encode_msg(
                                {"error": f"{type(e).__name__}: {e}"})):
                            return
                finally:
                    if buf is not None:
                        pool.release(buf)

        def work_outer():
            try:
                work()
            except (ConnectionError, OSError):
                pass
            finally:
                worker_dead.set()
                try:  # unblock a reader stuck in put() on a full queue;
                    # release any pooled buffers still queued so the
                    # reader's pool.acquire can't deadlock either
                    while True:
                        item = requests.get_nowait()
                        if item is not _EOF and item[2] is not None:
                            pool.release(item[2])
                except _queue.Empty:
                    pass

        def put_alive(item) -> bool:
            """put() that gives up once the worker is gone."""
            while not worker_dead.is_set():
                try:
                    requests.put(item, timeout=0.2)
                    return True
                except _queue.Full:
                    continue
            return False

        worker = threading.Thread(target=work_outer, daemon=True)
        worker.start()
        try:
            while not self._stop.is_set():
                header, buffers, buf = _recv_msg(
                    conn, pool,
                    dead=lambda: (worker_dead.is_set()
                                  or self._stop.is_set()))
                if header is None:
                    break
                if not put_alive((header, buffers, buf)):
                    if buf is not None:
                        pool.release(buf)
                    break
        except (ConnectionError, OSError):
            pass
        finally:
            put_alive(_EOF)
            worker.join(timeout=30)
            writer.close(join_timeout=30)
            conn.close()
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)


class PredictorClient:
    """Client for PredictorServer; supports request pipelining.

    infer(feed) is the blocking RPC. For pipelined throughput, call
    send(feed) repeatedly and then recv() for each — responses arrive in
    order on one connection, so K in-flight requests hide the round trip.
    """

    def __init__(self, host: str, port: int):
        self._sock = socket.create_connection((host, port))
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()  # serializes concurrent send()s

    def send(self, feed: Dict[str, Any],
             fetch: Optional[Sequence[str]] = None):
        arrays = {n: np.ascontiguousarray(v) for n, v in feed.items()}
        header = {"feeds": [{"name": n, "dtype": str(a.dtype),
                             "shape": list(a.shape)}
                            for n, a in arrays.items()]}
        if fetch is not None:
            header["fetch"] = list(fetch)
        with self._lock:
            # arrays ride by reference: one vectored sendmsg, no tobytes()
            _send_msg(self._sock, header, list(arrays.values()))

    def recv(self) -> List[np.ndarray]:
        header, buffers = _recv_msg(self._sock)
        if header is None:
            raise ConnectionError("server closed the connection")
        if "error" in header:
            raise RuntimeError(f"server error: {header['error']}")
        return [np.frombuffer(raw, dtype=np.dtype(spec["dtype"]))
                .reshape(spec["shape"])
                for spec, raw in zip(header["outs"], buffers)]

    def infer(self, feed: Dict[str, Any],
              fetch: Optional[Sequence[str]] = None) -> List[np.ndarray]:
        self.send(feed, fetch)
        return self.recv()

    def close(self):
        self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
