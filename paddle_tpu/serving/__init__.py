"""The serving subsystem: transport, engines, and the paged KV cache.

Grown out of the r07 `serving.py`/`serving_engine.py` pair into a
package (ISSUE r20 tentpole):

- `transport`  — the request/response RPC layer (`PredictorServer` /
  `PredictorClient`, v2 vectored framing). The old `paddle_tpu.serving`
  module surface — every public name is re-exported here, so existing
  imports keep working.
- `engine`     — the continuous-batching generation engine over the
  slot-indexed KV cache (`ContinuousBatchingEngine`, `EngineServer`,
  `EngineClient`). The old `paddle_tpu.serving_engine` module (a compat
  shim remains at that path).
- `kv_pager`   — the paged KV-cache subsystem: a device-resident block
  pool of fixed `block_size`-token pages, per-request block tables, a
  free-list allocator with LRU eviction of cached prefix blocks, a
  prefix-sharing radix index with copy-on-write at the divergence
  block, and `PagedKVEngine` — the engine that decodes through it
  (token-identical to the slot engine, at a fraction of the KV bytes
  per request; `BENCH_SERVE_KV_r20.json`).
- `sanitizer`  — the shadow-state sanitizer over the paged KV stack
  (r24): with the `kv_sanitize` flag on (`PTPU_KV_SANITIZE=1`), every
  `KVPager` mirrors its block-lifetime mutations against the abstract
  ownership model (`framework/ownership.py`) and raises
  `SanitizerDivergence` naming op/block/invariant on the first drift.
- `speculative` — speculative decoding over either engine
  (`SpecConfig`, `SpeculativeDecoder`): a quantized draft twin proposes
  γ tokens, one γ+1-wide target forward verifies, rejected paged blocks
  roll back through the pager (greedy mode token-identical to plain
  decode; `BENCH_SPEC_r22.json`).
"""

from __future__ import annotations

# -- transport: the full old `paddle_tpu.serving` surface ------------------
from .transport import (  # noqa: F401
    PredictorClient,
    PredictorServer,
    _BatchingWriter,
    _RecvBufferPool,
    _byte_views,
    _encode_msg,
    _recv_exact,
    _recv_exact_into,
    _recv_msg,
    _send_msg,
    _sendall_vec,
)

# -- engine ----------------------------------------------------------------
from .engine import (  # noqa: F401
    ContinuousBatchingEngine,
    EngineClient,
    EngineServer,
    GenRequest,
    SlotAllocator,
    scrape_healthz,
    scrape_metrics,
)

# -- speculative decoding --------------------------------------------------
from .speculative import (  # noqa: F401
    SpecConfig,
    SpeculativeDecoder,
    rejection_sample,
)

# -- paged KV cache --------------------------------------------------------
from .kv_pager import (  # noqa: F401
    BlockPool,
    BlockTable,
    HostTierConfig,
    KVPager,
    PagedKVEngine,
    RadixPrefixIndex,
    paged_beam_search,
)

# -- shadow-state sanitizer (r24) ------------------------------------------
from .sanitizer import (  # noqa: F401
    KVSanitizer,
    SanitizerDivergence,
)
