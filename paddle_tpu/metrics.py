"""Python-side metric accumulators.

≙ reference python/paddle/fluid/metrics.py (MetricBase, CompositeMetric,
Precision, Recall, Accuracy, ChunkEvaluator, EditDistance, Auc, DetectionMAP).
These accumulate *host-side* over minibatch fetch results; the in-graph
counterparts live in ops/metric_ops.py (accuracy/auc/precision_recall ops).
"""

from __future__ import annotations

import numpy as np

from .core.enforce import InvalidArgumentError, enforce


def _to_numpy(x):
    return np.asarray(x)


class MetricBase:
    """Base: states are attributes not starting with '_'; reset() zeroes them.

    ≙ metrics.py MetricBase (get_config/reset/update/eval contract).
    """

    def __init__(self, name=None):
        self._name = str(name) if name is not None else self.__class__.__name__

    def __str__(self):
        return "MetricBase: %s" % self._name

    def get_config(self):
        states = {a: v for a, v in self.__dict__.items()
                  if not a.startswith("_")}
        config = {"name": self._name, "states": states}
        return config

    def reset(self):
        for attr, value in self.__dict__.items():
            if attr.startswith("_"):
                continue
            if isinstance(value, (int, float)):
                setattr(self, attr, type(value)(0))
            elif isinstance(value, (np.ndarray,)):
                setattr(self, attr, np.zeros_like(value))
            elif isinstance(value, (tuple, list)):
                setattr(self, attr, type(value)())

    def update(self, preds, labels):
        raise NotImplementedError(
            "Should not use it directly, please extend it.")

    def eval(self):
        raise NotImplementedError(
            "Should not use it directly, please extend it.")


class CompositeMetric(MetricBase):
    """Evaluate several metrics over the same preds/labels."""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        enforce(isinstance(metric, MetricBase),
                "metric should be an instance of MetricBase",
                exc=InvalidArgumentError)
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    """Binary-classification precision: tp / (tp + fp)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(_to_numpy(preds)).reshape(-1).astype("int64")
        labels = _to_numpy(labels).reshape(-1).astype("int64")
        enforce(preds.shape == labels.shape,
                "preds/labels shape mismatch", exc=InvalidArgumentError)
        pos = preds == 1
        self.tp += int(np.sum(pos & (labels == 1)))
        self.fp += int(np.sum(pos & (labels != 1)))

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap != 0 else 0.0


class Recall(MetricBase):
    """Binary-classification recall: tp / (tp + fn)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(_to_numpy(preds)).reshape(-1).astype("int64")
        labels = _to_numpy(labels).reshape(-1).astype("int64")
        enforce(preds.shape == labels.shape,
                "preds/labels shape mismatch", exc=InvalidArgumentError)
        truth = labels == 1
        self.tp += int(np.sum(truth & (preds == 1)))
        self.fn += int(np.sum(truth & (preds != 1)))

    def eval(self):
        recall = self.tp + self.fn
        return float(self.tp) / recall if recall != 0 else 0.0


class Accuracy(MetricBase):
    """Running weighted mean of minibatch accuracies (feed the value the
    in-graph `accuracy` op fetched, plus the minibatch weight)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.value = .0
        self.weight = .0

    def update(self, value, weight):
        enforce(np.isscalar(weight) or np.asarray(weight).size == 1,
                "weight must be a scalar", exc=InvalidArgumentError)
        weight = float(np.asarray(weight).reshape(()))
        enforce(weight >= 0, "weight must be non-negative",
                exc=InvalidArgumentError)
        self.value += float(np.asarray(value).reshape(())) * weight
        self.weight += weight

    def eval(self):
        enforce(self.weight != 0,
                "There is no data in Accuracy Metrics; call update first",
                exc=InvalidArgumentError)
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    """Accumulate counts from the chunk_eval op: precision/recall/F1 over
    chunks (IOB-style sequence labeling)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).reshape(()))
        self.num_label_chunks += int(np.asarray(num_label_chunks).reshape(()))
        self.num_correct_chunks += int(
            np.asarray(num_correct_chunks).reshape(()))

    def eval(self):
        precision = (float(self.num_correct_chunks) / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (float(self.num_correct_chunks) / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1_score = (2 * precision * recall / (precision + recall)
                    if self.num_correct_chunks else 0.0)
        return precision, recall, f1_score


class EditDistance(MetricBase):
    """Average edit distance + instance error rate, fed from the
    edit_distance op output (distances [N,1], seq_num)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = .0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = _to_numpy(distances).astype("float64").reshape(-1)
        seq_num = int(np.asarray(seq_num).reshape(()))
        self.total_distance += float(np.sum(distances))
        self.seq_num += seq_num
        self.instance_error += int(np.sum(distances > 0))

    def eval(self):
        enforce(self.seq_num != 0,
                "There is no data in EditDistance Metric; call update first",
                exc=InvalidArgumentError)
        avg_distance = self.total_distance / self.seq_num
        avg_instance_error = self.instance_error / float(self.seq_num)
        return avg_distance, avg_instance_error


class Auc(MetricBase):
    """Host-side streaming AUC over threshold buckets (≙ metrics.py Auc;
    the in-graph `auc` op is the compiled counterpart)."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._curve = curve
        self._num_thresholds = num_thresholds
        _num_pred_buckets = num_thresholds + 1
        self._stat_pos = np.zeros(_num_pred_buckets, dtype="int64")
        self._stat_neg = np.zeros(_num_pred_buckets, dtype="int64")

    def reset(self):
        self._stat_pos[:] = 0
        self._stat_neg[:] = 0

    def update(self, preds, labels):
        preds = _to_numpy(preds)
        labels = _to_numpy(labels).reshape(-1)
        pos_prob = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
        bucket = np.clip((pos_prob * self._num_thresholds).astype("int64"),
                         0, self._num_thresholds)
        pos_mask = labels > 0
        np.add.at(self._stat_pos, bucket[pos_mask], 1)
        np.add.at(self._stat_neg, bucket[~pos_mask], 1)

    @staticmethod
    def trapezoid_area(x1, x2, y1, y2):
        return abs(x1 - x2) * (y1 + y2) / 2.0

    def eval(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        idx = self._num_thresholds
        while idx >= 0:
            tot_pos_prev = tot_pos
            tot_neg_prev = tot_neg
            tot_pos += self._stat_pos[idx]
            tot_neg += self._stat_neg[idx]
            auc += self.trapezoid_area(tot_neg, tot_neg_prev, tot_pos,
                                       tot_pos_prev)
            idx -= 1
        return (auc / tot_pos / tot_neg
                if tot_pos > 0.0 and tot_neg > 0.0 else 0.0)


class DetectionMAP(MetricBase):
    """Mean average precision for detection, accumulated host-side from
    (detections, gt boxes) minibatch results.

    detections: [M, 6] rows (label, score, xmin, ymin, xmax, ymax) with a
    per-image row-count list; gts: [G, 5] rows (label, xmin, ymin, xmax, ymax)
    with per-image counts. ≙ metrics.py DetectionMAP (the reference wires an
    in-graph detection_map op; here evaluation is host-side numpy).
    """

    def __init__(self, name=None, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral"):
        super().__init__(name)
        enforce(ap_version in ("integral", "11point"),
                "ap_version must be 'integral' or '11point'",
                exc=InvalidArgumentError)
        self._overlap_threshold = overlap_threshold
        self._evaluate_difficult = evaluate_difficult
        self._ap_version = ap_version
        # per class: list of (score, is_tp); and total gt count
        self._score_tp = {}
        self._gt_counts = {}

    def reset(self):
        self._score_tp = {}
        self._gt_counts = {}

    @staticmethod
    def _iou(box, boxes):
        if boxes.size == 0:
            return np.zeros((0,), dtype="float64")
        ixmin = np.maximum(boxes[:, 0], box[0])
        iymin = np.maximum(boxes[:, 1], box[1])
        ixmax = np.minimum(boxes[:, 2], box[2])
        iymax = np.minimum(boxes[:, 3], box[3])
        iw = np.maximum(ixmax - ixmin, 0.0)
        ih = np.maximum(iymax - iymin, 0.0)
        inter = iw * ih
        area = ((box[2] - box[0]) * (box[3] - box[1]) +
                (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1]) -
                inter)
        return inter / np.maximum(area, 1e-10)

    def update(self, detections, det_counts, gts, gt_counts):
        detections = _to_numpy(detections).reshape(-1, 6)
        gts = _to_numpy(gts).reshape(-1, 5)
        d0 = g0 = 0
        for dc, gc in zip(det_counts, gt_counts):
            det_i = detections[d0:d0 + dc]
            gt_i = gts[g0:g0 + gc]
            d0 += dc
            g0 += gc
            for cls in np.unique(gt_i[:, 0]).astype("int64"):
                self._gt_counts[int(cls)] = (self._gt_counts.get(int(cls), 0) +
                                             int(np.sum(gt_i[:, 0] == cls)))
            for cls in np.unique(det_i[:, 0]).astype("int64"):
                cls = int(cls)
                dcls = det_i[det_i[:, 0] == cls]
                gcls = gt_i[gt_i[:, 0] == cls][:, 1:5]
                order = np.argsort(-dcls[:, 1])
                matched = np.zeros(len(gcls), dtype=bool)
                rec = self._score_tp.setdefault(cls, [])
                for row in dcls[order]:
                    ious = self._iou(row[2:6], gcls)
                    best = int(np.argmax(ious)) if ious.size else -1
                    if (best >= 0 and ious[best] >= self._overlap_threshold
                            and not matched[best]):
                        matched[best] = True
                        rec.append((float(row[1]), 1))
                    else:
                        rec.append((float(row[1]), 0))

    def eval(self):
        aps = []
        for cls, n_gt in self._gt_counts.items():
            rec = self._score_tp.get(cls, [])
            if n_gt == 0:
                continue
            if not rec:
                aps.append(0.0)
                continue
            arr = np.array(sorted(rec, key=lambda t: -t[0]), dtype="float64")
            tp = np.cumsum(arr[:, 1])
            fp = np.cumsum(1 - arr[:, 1])
            recall = tp / n_gt
            precision = tp / np.maximum(tp + fp, 1e-10)
            if self._ap_version == "11point":
                ap = 0.0
                for t in np.linspace(0, 1, 11):
                    p = precision[recall >= t]
                    ap += (np.max(p) if p.size else 0.0) / 11.0
            else:
                # integral/VOC-style: sum precision deltas over recall
                mrec = np.concatenate(([0.0], recall, [recall[-1]]))
                mpre = np.concatenate(([0.0], precision, [0.0]))
                for i in range(len(mpre) - 2, -1, -1):
                    mpre[i] = max(mpre[i], mpre[i + 1])
                idx = np.where(mrec[1:] != mrec[:-1])[0]
                ap = float(np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]))
            aps.append(ap)
        return float(np.mean(aps)) if aps else 0.0
