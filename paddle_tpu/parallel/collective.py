"""Collective primitives over mesh axes.

≙ reference operators/nccl_op.cc:24-93 (raw AllReduce/Reduce/Bcast ops) and
platform/nccl_helper.h — except on TPU these are *compiled into* the program
as XLA HLO collectives riding the ICI, not runtime library calls. These
wrappers exist so higher layers (tensor_parallel, pipeline, ring_attention)
speak one vocabulary; inside `shard_map` they lower to psum/all_gather/
ppermute HLOs.
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
from jax.sharding import PartitionSpec as P

from .mesh import DeviceMesh, shard_map


def all_reduce(x, axis_name: str):
    """Sum across an axis (≙ ncclAllReduce, all_reduce_op_handle.cc)."""
    return jax.lax.psum(x, axis_name)


def all_reduce_mean(x, axis_name: str):
    return jax.lax.pmean(x, axis_name)


def reduce_scatter(x, axis_name: str, scatter_dim: int = 0):
    """≙ the Reduce-to-owner half of ReduceOpHandle (reduce_op_handle.h:34),
    generalized: every shard owns a slice of the reduction."""
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dim,
                                tiled=True)


def all_gather(x, axis_name: str, gather_dim: int = 0):
    """≙ BroadcastOpHandle capability (broadcast_op_handle.h:35)."""
    return jax.lax.all_gather(x, axis_name, axis=gather_dim, tiled=True)


def all_to_all(x, axis_name: str, split_dim: int, concat_dim: int):
    return jax.lax.all_to_all(x, axis_name, split_axis=split_dim,
                              concat_axis=concat_dim, tiled=True)


def ppermute(x, axis_name: str, perm: Sequence[tuple]):
    return jax.lax.ppermute(x, axis_name, perm=perm)


def ring_perm(axis_size: int) -> list:
    """The forward ring permutation shard i -> (i+1) % n — the one schedule
    shared by ring attention and the pipeline."""
    return [(i, (i + 1) % axis_size) for i in range(axis_size)]


def shift_right(x, axis_name: str, axis_size: int):
    """Ring shift: shard i -> shard (i+1) % n. Building block for ring
    attention and pipelining."""
    return jax.lax.ppermute(x, axis_name, perm=ring_perm(axis_size))


def shift_left(x, axis_name: str, axis_size: int):
    perm = [((i + 1) % axis_size, i) for i in range(axis_size)]
    return jax.lax.ppermute(x, axis_name, perm=perm)


def axis_index(axis_name: str):
    return jax.lax.axis_index(axis_name)


def sharded(mesh: DeviceMesh, in_specs, out_specs,
            check_rep: bool = False) -> Callable:
    """Decorator: run fn as per-shard SPMD code over `mesh` (shard_map).

    This is the escape hatch from the "annotate & let XLA partition" world
    into explicit per-device code — used where the collective schedule IS the
    algorithm (ring attention, pipeline), mirroring how the reference drops
    from graph building into hand-written op handles.
    """
    def deco(fn):
        smapped = shard_map(fn, mesh=mesh.jax_mesh, in_specs=in_specs,
                            out_specs=out_specs, check_rep=check_rep)
        return functools.wraps(fn)(smapped)
    return deco


