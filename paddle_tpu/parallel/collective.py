"""Collective primitives over mesh axes.

≙ reference operators/nccl_op.cc:24-93 (raw AllReduce/Reduce/Bcast ops) and
platform/nccl_helper.h — except on TPU these are *compiled into* the program
as XLA HLO collectives riding the ICI, not runtime library calls. These
wrappers exist so higher layers (tensor_parallel, pipeline, ring_attention)
speak one vocabulary; inside `shard_map` they lower to psum/all_gather/
ppermute HLOs.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.enforce import InvalidArgumentError, enforce
from .mesh import DeviceMesh, shard_map


def axis_size(axis_name: str) -> int:
    """Concrete size of a named mesh axis, valid inside shard_map/pmap
    (psum of the literal 1 constant-folds to the axis size at trace time)."""
    return jax.lax.psum(1, axis_name)


def all_reduce(x, axis_name: str):
    """Sum across an axis (≙ ncclAllReduce, all_reduce_op_handle.cc)."""
    return jax.lax.psum(x, axis_name)


def all_reduce_mean(x, axis_name: str):
    return jax.lax.pmean(x, axis_name)


def reduce_scatter(x, axis_name: str, scatter_dim: int = 0):
    """≙ the Reduce-to-owner half of ReduceOpHandle (reduce_op_handle.h:34),
    generalized: every shard owns a slice of the reduction."""
    n = axis_size(axis_name)
    # guards raise with full context but build their message only on the
    # failing path — these run inside traced hot loops (same de-f-string
    # discipline as memory.update_watermark)
    if not 0 <= scatter_dim < x.ndim:
        raise InvalidArgumentError(
            f"reduce_scatter: scatter_dim {scatter_dim} out of range for "
            f"rank-{x.ndim} input")
    if x.shape[scatter_dim] % n != 0:
        raise InvalidArgumentError(
            f"reduce_scatter: dim {scatter_dim} of shape {tuple(x.shape)} is "
            f"not divisible by the {axis_name!r} axis size {n}; pad the "
            f"scattered dimension to a multiple of {n} (each shard owns an "
            f"equal slice of the reduction) or scatter a different dim")
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dim,
                                tiled=True)


def all_gather(x, axis_name: str, gather_dim: int = 0):
    """≙ BroadcastOpHandle capability (broadcast_op_handle.h:35)."""
    return jax.lax.all_gather(x, axis_name, axis=gather_dim, tiled=True)


def all_to_all(x, axis_name: str, split_dim: int, concat_dim: int):
    return jax.lax.all_to_all(x, axis_name, split_axis=split_dim,
                              concat_axis=concat_dim, tiled=True)


def ppermute(x, axis_name: str, perm: Sequence[tuple]):
    return jax.lax.ppermute(x, axis_name, perm=perm)


def ring_perm(axis_size: int) -> list:
    """The forward ring permutation shard i -> (i+1) % n — the one schedule
    shared by ring attention and the pipeline."""
    return [(i, (i + 1) % axis_size) for i in range(axis_size)]


def shift_right(x, axis_name: str, axis_size: int):
    """Ring shift: shard i -> shard (i+1) % n. Building block for ring
    attention and pipelining."""
    return jax.lax.ppermute(x, axis_name, perm=ring_perm(axis_size))


def shift_left(x, axis_name: str, axis_size: int):
    perm = [((i + 1) % axis_size, i) for i in range(axis_size)]
    return jax.lax.ppermute(x, axis_name, perm=perm)


def axis_index(axis_name: str):
    return jax.lax.axis_index(axis_name)


def sharded(mesh: DeviceMesh, in_specs, out_specs,
            check_rep: bool = False) -> Callable:
    """Decorator: run fn as per-shard SPMD code over `mesh` (shard_map).

    This is the escape hatch from the "annotate & let XLA partition" world
    into explicit per-device code — used where the collective schedule IS the
    algorithm (ring attention, pipeline), mirroring how the reference drops
    from graph building into hand-written op handles.
    """
    def deco(fn):
        smapped = shard_map(fn, mesh=mesh.jax_mesh, in_specs=in_specs,
                            out_specs=out_specs, check_rep=check_rep)
        return functools.wraps(fn)(smapped)
    return deco


# ---------------------------------------------------------------------------
# Quantized collectives (block-scaled compress -> collective -> decompress).
#
# ≙ EQuARX (PAPERS.md): on the wire a gradient travels as int8 payload plus
# one f32 scale per block instead of f32 — ~4x fewer bytes with block-local
# dynamic range. The cross-replica SUM is decomposed into the same two
# phases XLA uses for a ring all-reduce (reduce-scatter, then all-gather),
# but each phase's transfer is quantized by US before it hits the wire:
#
#   phase 1: every shard splits its local partial into `axis` chunks,
#            quantizes each destination chunk independently, all_to_all's
#            the (payload, scales) pair, and dequant-sums what it received
#            -> shard i owns the fully reduced chunk i, fp32.
#   phase 2: the owner re-quantizes its reduced chunk and all_gather's it.
#
# The fp32 accumulation in phase 1 keeps the sum exact given the quantized
# contributions (no int overflow, no precision loss across `axis` adds);
# the only approximation is the two quantization steps, which the optional
# error-feedback state (grad_comm.py) compensates across steps.
# ---------------------------------------------------------------------------

QUANT_BLOCK = 256           # default block: one f32 scale per 256 values
_QUANT_WIRE_DTYPES = ("int8", "bf16")


def quantize_blocks(flat, block: int = QUANT_BLOCK):
    """Block-scaled symmetric int8 quantization of a flat f32 vector whose
    length is a multiple of `block`. Returns (q int8 [n//block, block],
    scales f32 [n//block, 1]); zero blocks get scale 1 so they stay exact."""
    if flat.ndim != 1 or flat.shape[0] % block != 0:
        raise InvalidArgumentError(
            f"quantize_blocks wants a flat block-multiple vector, got shape "
            f"{tuple(flat.shape)} for block {block}")
    xb = flat.reshape(-1, block)
    amax = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(xb / scale), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_blocks(q, scale):
    """Inverse of quantize_blocks: flat f32 vector."""
    return (q.astype(jnp.float32) * scale).reshape(-1)


# ---------------------------------------------------------------------------
# 2-D block quantization for weights-at-rest (r21 weight-only serving).
#
# The wire path above scales per contiguous 1-D run; weights want per-tile
# scales so a single outlier row does not flatten a whole matrix. Tiles are
# (br, bc) sub-blocks of the 2-D weight; each tile gets one f32 scale.
# Int4 halves the payload again by packing two nibbles per int8 byte along
# the column axis (column count must be even).
# ---------------------------------------------------------------------------

QUANT_BLOCK_2D = 64         # default tile edge: one f32 scale per <=64x64 tile


def block_dims_2d(shape, block: int = QUANT_BLOCK_2D):
    """Largest tile dims <= `block` that divide each axis of `shape` exactly
    (falls back toward 1, which always divides), so payloads keep the exact
    declared weight shape — no padding bytes to reconcile in the census."""
    def fit(n):
        b = min(block, n)
        while n % b:
            b -= 1
        return b
    return fit(shape[0]), fit(shape[1])


def quantize_blocks_2d(w, bits: int = 8, block: int = QUANT_BLOCK_2D):
    """Tile-scaled symmetric quantization of a 2-D f32 matrix.

    Returns (payload int8 [R, C] — or [R, C//2] nibble-packed when bits=4 —
    and scales f32 [R//br, C//bc]). Zero tiles get scale 1 so they stay
    exact; int4 clips to [-7, 7] before packing.
    """
    if w.ndim != 2:
        raise InvalidArgumentError(
            f"quantize_blocks_2d wants a 2-D matrix, got shape "
            f"{tuple(w.shape)}")
    if bits not in (8, 4):
        raise InvalidArgumentError(
            f"quantize_blocks_2d supports bits in (8, 4), got {bits}")
    r, c = w.shape
    if bits == 4 and c % 2 != 0:
        raise InvalidArgumentError(
            f"int4 packing needs an even column count, got shape "
            f"{tuple(w.shape)}")
    br, bc = block_dims_2d(w.shape, block)
    t = jnp.asarray(w, jnp.float32).reshape(r // br, br, c // bc, bc)
    amax = jnp.max(jnp.abs(t), axis=(1, 3), keepdims=True)
    qmax = 127.0 if bits == 8 else 7.0
    scale = jnp.where(amax > 0, amax / qmax, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(t / scale), -qmax, qmax).astype(jnp.int8)
    q = q.reshape(r, c)
    if bits == 4:
        q = pack_int4(q)
    return q, scale.reshape(r // br, c // bc)


def dequantize_blocks_2d(q, scales, bits: int = 8):
    """Inverse of quantize_blocks_2d: f32 matrix [R, C]. `scales` carries the
    tile grid [R//br, C//bc]; the payload is nibble-unpacked when bits=4."""
    if bits == 4:
        q = unpack_int4(q)
    r, c = q.shape
    nr, nc = scales.shape
    t = q.astype(jnp.float32).reshape(nr, r // nr, nc, c // nc)
    return (t * scales[:, None, :, None]).reshape(r, c)


def pack_int4(q):
    """Pack an int8 matrix with values in [-7, 7] into nibbles: columns
    (2k, 2k+1) share byte k as (low, high). Returns int8 [R, C//2]."""
    lo = q[:, 0::2]
    hi = q[:, 1::2]
    return ((lo & jnp.int8(0x0F)) | (hi << 4)).astype(jnp.int8)


def unpack_int4(p):
    """Inverse of pack_int4: int8 [R, C2] -> int8 [R, 2*C2]. Sign-extends
    each nibble via arithmetic shifts (two's complement)."""
    lo = ((p << 4).astype(jnp.int8) >> 4).astype(jnp.int8)
    hi = (p >> 4).astype(jnp.int8)
    return jnp.stack([lo, hi], axis=-1).reshape(p.shape[0], -1)


def _compress(flat, wire_dtype: str, block: int):
    """flat f32 -> (payload, scales-or-None) in the wire dtype."""
    if wire_dtype == "int8":
        return quantize_blocks(flat, block)
    if wire_dtype == "bf16":
        return flat.astype(jnp.bfloat16), None
    raise InvalidArgumentError(
        f"unknown comm wire dtype {wire_dtype!r}; "
        f"expected one of {_QUANT_WIRE_DTYPES}")


def _decompress(payload, scales):
    if scales is None:
        return payload.astype(jnp.float32).reshape(-1)
    return dequantize_blocks(payload, scales)


def _pin_wire(payload, scales):
    """Best-effort pin of the COMPRESSED dtype on the wire. The bf16
    path is an exact round-trip (the f32 -> bf16 -> f32 widening loses
    nothing the narrowing didn't already drop), so a simplifier may
    legally commute the widening convert across the collective; the
    optimization barriers keep each convert on its own side of the
    transfer on backends whose collectives carry bf16 natively (TPU).
    KNOWN LIMIT, census-measured (r19 planner bench): this container's
    jaxlib-0.4.x CPU backend promotes the bf16 collective payload to
    f32 REGARDLESS (it inserts its own converts and elides the
    barriers), so on the CPU mesh the bf16 wire census reads exactly 2x
    the analytic model — which is why the auto-parallel planner's
    DEFAULT space searches int8 but not bf16 (auto_parallel.
    SearchSpace); the bf16 claim stays a TPU re-measure item. int8
    needs no pin: its dequant multiplies by per-block scales, which
    nothing can hoist."""
    if scales is None:
        payload = jax.lax.optimization_barrier(payload)
    return payload


def compressed_size_ratio(wire_dtype: str, block: int = QUANT_BLOCK) -> float:
    """Analytic bytes-on-wire ratio vs f32 for one compressed transfer."""
    if wire_dtype == "int8":
        return (1.0 + 4.0 / block) / 4.0
    if wire_dtype == "bf16":
        return 0.5
    return 1.0


def quantized_reduce_scatter_flat(flat, axis_name: str, *,
                                  wire_dtype: str = "int8",
                                  block: int = QUANT_BLOCK,
                                  mean: bool = False):
    """Phase 1 of the quantized all-reduce: each shard contributes its local
    partial `flat` (length divisible by the axis size) and receives the fully
    reduced chunk it owns, fp32, length len(flat)//axis_size. Each
    destination chunk is compressed independently (block padding included) so
    the chunk boundary never splits a scale block."""
    n = axis_size(axis_name)
    if flat.ndim != 1 or flat.shape[0] % n != 0:
        raise InvalidArgumentError(
            f"quantized_reduce_scatter_flat wants a flat vector divisible by "
            f"the {axis_name!r} axis size {n}, got {tuple(flat.shape)}")
    chunk = flat.shape[0] // n
    cpad = -(-chunk // block) * block
    xb = flat.reshape(n, chunk)
    xb = jnp.pad(xb, ((0, 0), (0, cpad - chunk)))
    payload, scales = _compress(xb.reshape(-1), wire_dtype, block)
    # all_to_all the per-destination compressed chunks: shard i ends up
    # holding every peer's compressed version of chunk i
    payload = _pin_wire(payload, scales)
    payload = payload.reshape(n, -1, *payload.shape[1:])
    payload = jax.lax.all_to_all(payload, axis_name, split_axis=0,
                                 concat_axis=0, tiled=True)
    payload = _pin_wire(payload, scales)
    if scales is not None:
        scales = scales.reshape(n, -1, *scales.shape[1:])
        scales = jax.lax.all_to_all(scales, axis_name, split_axis=0,
                                    concat_axis=0, tiled=True)
        part = (payload.astype(jnp.float32) * scales)
    else:
        part = payload.astype(jnp.float32)
    part = part.reshape(n, cpad).sum(axis=0)[:chunk]
    if mean:
        part = part / n
    return part


def quantization_residual_flat(flat, n: int, *, wire_dtype: str = "int8",
                               block: int = QUANT_BLOCK):
    """What phase 1 loses for THIS shard's contribution: flat minus the
    dequantized form of its compressed transfer, under the exact
    per-destination-chunk padded block layout quantized_reduce_scatter_flat
    puts on the wire. This is the error-feedback accumulator's update."""
    chunk = flat.shape[0] // n
    cpad = -(-chunk // block) * block
    xb = jnp.pad(flat.reshape(n, chunk), ((0, 0), (0, cpad - chunk)))
    payload, scales = _compress(xb.reshape(-1), wire_dtype, block)
    deq = _decompress(payload, scales).reshape(n, cpad)[:, :chunk]
    return flat - deq.reshape(-1)


def quantized_all_gather_flat(chunk, axis_name: str, *,
                              wire_dtype: str = "int8",
                              block: int = QUANT_BLOCK):
    """Phase 2: compress the owned chunk, all_gather, decompress. Returns the
    concatenation over shards, fp32, length len(chunk) * axis_size."""
    n = axis_size(axis_name)
    c = chunk.shape[0]
    cpad = -(-c // block) * block
    padded = jnp.pad(chunk, (0, cpad - c))
    payload, scales = _compress(padded, wire_dtype, block)
    payload = _pin_wire(payload, scales)
    payload = jax.lax.all_gather(payload, axis_name, axis=0, tiled=True)
    payload = _pin_wire(payload, scales)
    if scales is not None:
        scales = jax.lax.all_gather(scales, axis_name, axis=0, tiled=True)
    full = _decompress(payload, scales).reshape(n, cpad)[:, :c]
    return full.reshape(-1)


def quantized_all_reduce_flat(flat, axis_name: str, *,
                              wire_dtype: str = "int8",
                              block: int = QUANT_BLOCK,
                              mean: bool = False):
    """Block-scaled quantized all-reduce of a flat vector (length divisible
    by the axis size): quantized reduce-scatter + quantized all-gather.
    Wire bytes ~= 2 * len(flat) * (1 + 4/block) for int8 vs 8 * len(flat)
    for the fp32 ring equivalent."""
    part = quantized_reduce_scatter_flat(flat, axis_name,
                                         wire_dtype=wire_dtype, block=block,
                                         mean=mean)
    return quantized_all_gather_flat(part, axis_name, wire_dtype=wire_dtype,
                                     block=block)


