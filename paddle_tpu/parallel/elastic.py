"""Elastic fault-tolerant training: atomic snapshots + preemption recovery.

Production scale means preemptible hardware (ROADMAP item 5). The
reference framework survives preemption through its parameter-server
checkpoint handler and per-trainer `_save_checkpoint` artifacts
(reference listen_and_serv_op.cc checkpoint handler + trainer.py:641);
the TPU-native reproduction checkpoints the arrays themselves — sharding
lives on them — and this module makes that crash-safe and elastic:

- `save_train_state` snapshots the COMPLETE training state: parameters,
  dp-sharded ZeRO-1 optimizer accumulators (`accumulator_of` backrefs),
  per-replica error-feedback residuals (`dp_comm_err_*`), the RNG
  seed/step counters that drive the executor's seed stream, and the
  BuildStrategy/mesh config — with an ATOMIC TWO-PHASE COMMIT: all files
  land in a hidden staging directory, every byte is fsync'd, the staging
  directory is renamed into place, and only then a `COMMIT` marker (an
  integrity record of every file and its size) is atomically renamed in.
  A kill at ANY byte offset leaves either a committed snapshot (which
  restores exactly) or an uncommitted one (which restore skips/rejects) —
  never a restorable half-write.
- the ASYNC path: the device→host copy happens synchronously at the step
  boundary (`sharded_checkpoint.collect_chunks`), then a background
  thread does the file writes and the commit, so the step critical path
  pays only the d2h copy. Every phase records a "checkpoint" span
  (observability/tracing.py) and save/restore durations + bytes land in
  this module's MetricsRegistry.
- MULTI-WRITER saves run the CHIEF-COMMITS BARRIER over a simulated
  process world (`save_train_state(world=ProcessWorld(N))`,
  parallel/process_world.py): every rank stages + fsyncs its OWN shard
  files in a rank-private staging dir and acks a per-file digest
  manifest to the chief; the chief waits with a deadline, binds every
  rank's manifest into ONE COMMIT record, and a single atomic rename
  makes the snapshot visible. A SIGKILL of any rank (chief included) at
  any phase, a straggler past the deadline, or a torn shard file leaves
  either a fully-restorable snapshot or a cleanly-rejected one — never a
  half-write; aborts are counted and training continues.
- `restore_train_state` is ELASTIC across ARBITRARY mesh changes: given
  an executor over a DIFFERENT dp × pp × tp world (dp2×tp2 → dp4,
  dp2×pp2 → dp2×tp2), each array is re-placed via
  `jax.make_array_from_callback` onto the new mesh (the r08 kill-switch
  state reconciliation, generalized across process boundaries), ZeRO-1
  optimizer slices re-shard automatically from their full-shape chunks,
  and error-feedback residuals are re-mapped across dp AND tp changes
  with the pending gradient mass preserved (see `_resize_replica_rows` /
  `_remap_error_feedback`). The re-layout is planned up front
  (parallel/reshard.py): per-variable read ranges + the equivalent
  collective redistribution schedule, validated exactly against
  `framework.costs.reshard_wire_bytes`. Before the first step the
  restored program's placement is verified statically through the
  r10/r13 analyzer (`verify_program`) and every restored array's
  sharding is checked against the executor's placement policy.
- `PTPU_FAULT_INJECT` makes preemption recovery TESTABLE: crash-at-step,
  crash-mid-save (SIGKILL at a chosen byte offset of the snapshot
  payload), slow-writer, and the world-aware per-rank/per-phase
  directives (crash_rank/drop_rank/straggle_rank,
  process_world.world_fault_plan). tests/test_elastic.py,
  tests/test_process_world.py and tools/recovery_smoke.py kill real
  processes through it.

Grounding (PAPERS.md): the ZeRO-1 shard layout that must round-trip is
"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training"; the N→M re-placement on restore is the checkpoint-mediated
form of "Memory-efficient array redistribution through portable
collective communication".

Directory layout (docs/fault_tolerance.md):

    <root>/
      snapshot-00000003/          committed snapshot, serial 3
        shard-<r>.pts             rank r's chunks (tensor_store)
        manifest-<r>.json         rank r's chunk -> global-offset map
        train_meta.json           step/seed counters, strategy, EF
                                  layout, per-var placements
        COMMIT                    atomic commit marker + integrity
                                  record (per-file sizes AND crc32
                                  digests, commit timestamp, world)
      .tmp-00000004-1234/         staging dir of an interrupted
                                  single-writer save
      .tmp-00000004-rank2/        rank 2's private staging (barrier)
      .tmp-00000004-world1234/    the chief's assembly dir (barrier)
"""

from __future__ import annotations

import json
import os
import re
import shutil
import signal
import threading
import time
import zlib
from typing import Any, Dict, List, Optional

import numpy as np

from ..core import flags
from ..core.enforce import InvalidArgumentError, NotFoundError, enforce

class SnapshotDigestError(InvalidArgumentError):
    """A committed snapshot file's content digest disagrees with the
    COMMIT integrity record (silent corruption after commit) — its own
    type so tooling (lint_program --restore_dir) classifies it
    structurally, not by matching error text."""


SNAPSHOT_PREFIX = "snapshot-"
STAGING_PREFIX = ".tmp-"
COMMIT_MARKER = "COMMIT"
META_FILE = "train_meta.json"
META_FORMAT = 2
#: version of the COMMIT integrity record THIS reader understands; a
#: snapshot committed by a newer protocol is skipped (warn-once), never
#: half-understood
COMMIT_FORMAT = 2


# ---------------------------------------------------------------------------
# fault injection (PTPU_FAULT_INJECT)
# ---------------------------------------------------------------------------

def fault_injection_config() -> Dict[str, float]:
    """Parse PTPU_FAULT_INJECT: comma-separated `directive:value` pairs.

      crash_at_step:<k>     SIGKILL self when maybe_crash_at_step(k) fires
      crash_mid_save:<b>    SIGKILL during the snapshot protocol at byte
                            offset b of the staged payload (b < payload:
                            truncated staging files; b == payload: after
                            the directory rename, BEFORE the COMMIT
                            marker; b > payload: just after commit)
      slow_writer:<s>       sleep s seconds in the background writer
                            before touching disk (widens the async
                            window; exercises drain paths)

    World-aware directives (crash_rank/drop_rank/straggle_rank) are
    parsed by process_world.world_fault_plan — they pass through here
    unchecked so one env var carries both families.

    Parsed per call — tests flip the env var between runs."""
    from .process_world import WORLD_DIRECTIVES
    raw = os.environ.get("PTPU_FAULT_INJECT", "")
    out: Dict[str, float] = {}
    if not raw:
        return out
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        enforce(":" in part,
                f"PTPU_FAULT_INJECT directive {part!r} must be "
                f"`name:value`", exc=InvalidArgumentError)
        name, val = part.split(":", 1)
        if name in WORLD_DIRECTIVES:
            continue  # structured values, owned by process_world
        enforce(name in ("crash_at_step", "crash_mid_save", "slow_writer"),
                f"unknown PTPU_FAULT_INJECT directive {name!r}",
                exc=InvalidArgumentError)
        out[name] = float(val)
    return out


def _sigkill_self():  # pragma: no cover - the process dies here
    os.kill(os.getpid(), signal.SIGKILL)


def maybe_crash_at_step(step: int):
    """Training loops call this once per step: under
    `PTPU_FAULT_INJECT=crash_at_step:<k>` the process SIGKILLs itself
    when step == k — the supervisor/recovery tests' preemption."""
    cfg = fault_injection_config()
    k = cfg.get("crash_at_step")
    if k is not None and int(step) == int(k):
        flags.vlog(0, "fault injection: SIGKILL at step %d", step)
        _sigkill_self()  # pragma: no cover


def _payload_files(staging: str) -> List[str]:
    """Deterministic order of the staged payload files the
    crash_mid_save byte offset indexes into."""
    names = sorted(n for n in os.listdir(staging)
                   if n != COMMIT_MARKER and not n.endswith(".tmp"))
    return names


def _crash_mid_staging(staging: str, offset: int) -> bool:
    """crash_mid_save with offset inside the payload: make the staging
    dir look exactly as if the writer died `offset` bytes into its
    sequential write (sharded_checkpoint.truncate_payload_at — shared
    with the world-aware crash_rank stage faults), then SIGKILL.
    Returns False when the offset lies beyond the payload (the caller
    crashes later in the protocol)."""
    from ..sharded_checkpoint import truncate_payload_at
    if not truncate_payload_at(staging, offset,
                               exclude=(COMMIT_MARKER,)):
        return False
    _sigkill_self()  # pragma: no cover
    return True


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

_registry = None
_reg_lock = threading.Lock()


def metrics_registry():
    """The checkpoint telemetry series: save/restore durations, bytes
    written, snapshots committed, barrier aborts, pending async writes.

    Since r16 these register into `observability.metrics
    .default_registry()` (idempotently) instead of a private registry,
    so ONE /metrics scrape sees checkpoint, training, and serving series
    together — this function now returns the default registry and is
    kept for API compatibility (every `ptpu_ckpt_*` lookup through it
    still resolves)."""
    global _registry
    with _reg_lock:
        if _registry is None:
            from ..observability import metrics as m
            r = m.default_registry()
            c = m.get_or_create
            c(r, "counter", "ptpu_ckpt_saves_total",
              "Snapshots committed by this process.")
            c(r, "counter", "ptpu_ckpt_save_bytes_total",
              "Payload bytes written across committed snapshots.")
            c(r, "counter", "ptpu_ckpt_restores_total",
              "Snapshots restored.")
            c(r, "counter", "ptpu_ckpt_barrier_aborts_total",
              "Multi-rank snapshot attempts aborted at the "
              "chief's barrier (straggler past the deadline or a "
              "dead rank); training continues, the snapshot is "
              "discarded.")
            c(r, "counter", "ptpu_ckpt_skipped_foreign_total",
              "Snapshot dirs skipped during latest-snapshot "
              "selection because their COMMIT record was written "
              "by a newer protocol/world config than this "
              "process understands.")
            c(r, "counter", "ptpu_ckpt_digest_failures_total",
              "Snapshot files whose content digest disagreed "
              "with the COMMIT integrity record (silent "
              "bit-flips caught at validate/restore).")
            c(r, "histogram", "ptpu_ckpt_save_seconds",
              "Wall time of the write+commit phase.",
              buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                       5.0, 10.0, 30.0))
            c(r, "histogram", "ptpu_ckpt_restore_seconds",
              "Wall time of restore_train_state.",
              buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                       5.0, 10.0, 30.0))
            c(r, "gauge", "ptpu_ckpt_pending_async",
              "Async snapshot writes not yet committed.",
              fn=lambda: float(len(_PENDING)))
            _registry = r
    return _registry


def pending_async_count() -> int:
    """In-flight async snapshot writes not yet committed — the number
    the serving /healthz endpoint reports as pending_checkpoints."""
    with _pending_lock:
        return len(_PENDING)


def _metric(name):
    return metrics_registry().get(name)


# ---------------------------------------------------------------------------
# snapshot directory bookkeeping
# ---------------------------------------------------------------------------

_SNAP_RE = re.compile(re.escape(SNAPSHOT_PREFIX) + r"(\d+)$")


def is_committed(dirname: str) -> bool:
    return os.path.exists(os.path.join(dirname, COMMIT_MARKER))


def file_digest(path: str) -> str:
    """Content digest recorded per file in the COMMIT integrity record:
    crc32 over the full file, rendered as 8 hex chars. Catches the
    silent bit-flips a size check cannot (cheap enough to verify on
    every restore)."""
    crc = 0
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(block, crc)
    return f"{crc & 0xFFFFFFFF:08x}"


def list_snapshots(root: str, committed_only: bool = True):
    """[(serial, path)] ascending by serial. committed_only=True (the
    default — restore's view) skips snapshot dirs without a COMMIT
    marker: an interrupted save must never be picked as "latest"."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        m = _SNAP_RE.match(name)
        if not m:
            continue
        path = os.path.join(root, name)
        if committed_only and not is_committed(path):
            continue
        out.append((int(m.group(1)), path))
    return sorted(out)


def _read_commit_record(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(os.path.join(path, COMMIT_MARKER)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return None


_warned_foreign: set = set()


def _ranked_snapshots(root: str) -> List[str]:
    """Committed snapshot paths ascending by (step, commit_ts, serial) —
    the ONE ranking shared by latest-snapshot selection AND retention,
    so retention can never delete the snapshot selection would pick.
    Directories whose COMMIT record declares a NEWER protocol format
    than this reader understands (a newer world config writing into the
    same root) are excluded — never selected, never pruned — with a
    warn-once vlog + the ptpu_ckpt_skipped_foreign_total counter:
    adopting (or deleting) a half-understood snapshot would turn the
    integrity story into noise."""
    ranked = []
    for serial, path in list_snapshots(root, committed_only=True):
        record = _read_commit_record(path) or {}
        fmt = int(record.get("format", 1))
        if fmt > COMMIT_FORMAT:
            if path not in _warned_foreign:
                _warned_foreign.add(path)
                flags.vlog(0, "skipping snapshot %s: COMMIT format %d is "
                           "newer than this process understands (%d) — "
                           "written by a newer world config?", path, fmt,
                           COMMIT_FORMAT)
                _metric("ptpu_ckpt_skipped_foreign_total").inc()
            continue
        key = (int(record.get("step", -1)),
               float(record.get("commit_ts", 0.0)), serial)
        ranked.append((key, path))
    return [p for _, p in sorted(ranked)]


def latest_snapshot(root: str) -> Optional[str]:
    """Path of the newest COMMITTED snapshot under root, or None.

    Deterministic under concurrent/stale writers: candidates order by
    (step, commit_ts, serial) from the COMMIT record — two snapshots at
    the SAME step (a stale supervisor racing a live one on one root)
    tie-break by commit timestamp, then serial, instead of whichever
    serial a racing _alloc_serial happened to mint last (see
    `_ranked_snapshots` for the foreign-format skip)."""
    ranked = _ranked_snapshots(root)
    return ranked[-1] if ranked else None


def _record_size_digest(entry) -> (int, Optional[str]):
    """A COMMIT `files` entry: format 1 recorded a bare byte size;
    format 2 records {"size": s, "crc32": "xxxxxxxx"}."""
    if isinstance(entry, dict):
        return int(entry["size"]), entry.get("crc32")
    return int(entry), None


def validate_snapshot(dirname: str, digests: bool = True):
    """Raise a clear enforce error unless `dirname` is a complete,
    committed snapshot: COMMIT marker present and parseable, every file
    it records present at exactly the recorded size AND (digests=True,
    the default) matching its recorded content digest — a silent
    bit-flip inside a shard container is rejected with an error naming
    the file, not surfaced as garbage weights — manifest count matching.
    The property the crash-anywhere tests pin: a directory that passes
    here restores exactly; one that fails is rejected with the directory
    and the missing/damaged piece named."""
    enforce(os.path.isdir(dirname),
            f"snapshot dir {dirname!r} does not exist",
            exc=NotFoundError)
    marker = os.path.join(dirname, COMMIT_MARKER)
    enforce(os.path.exists(marker),
            f"snapshot dir {dirname!r} has no {COMMIT_MARKER} marker — an "
            f"interrupted (uncommitted) save; it is not restorable. "
            f"restore_train_state(root) picks the latest COMMITTED "
            f"snapshot automatically", exc=InvalidArgumentError)
    try:
        with open(marker) as f:
            record = json.load(f)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise InvalidArgumentError(
            f"snapshot dir {dirname!r}: {COMMIT_MARKER} marker is corrupt "
            f"({e})") from e
    fmt = int(record.get("format", 1))
    enforce(fmt <= COMMIT_FORMAT,
            f"snapshot dir {dirname!r}: {COMMIT_MARKER} format {fmt} is "
            f"newer than this process understands ({COMMIT_FORMAT}) — "
            f"restore with the world config that wrote it",
            exc=InvalidArgumentError)
    files = record.get("files", {})
    for name, entry in files.items():
        path = os.path.join(dirname, name)
        enforce(os.path.exists(path),
                f"snapshot dir {dirname!r} is missing {name!r} recorded "
                f"in its {COMMIT_MARKER} marker",
                exc=InvalidArgumentError)
        size, digest = _record_size_digest(entry)
        got = os.path.getsize(path)
        enforce(got == size,
                f"snapshot dir {dirname!r}: {name!r} is {got} bytes but "
                f"the {COMMIT_MARKER} marker recorded {size} — truncated "
                f"or overwritten after commit",
                exc=InvalidArgumentError)
        if digests and digest is not None:
            got_digest = file_digest(path)
            if got_digest != digest:
                _metric("ptpu_ckpt_digest_failures_total").inc()
            enforce(got_digest == digest,
                    f"snapshot dir {dirname!r}: {name!r} content digest "
                    f"crc32:{got_digest} does not match the "
                    f"{COMMIT_MARKER} marker's crc32:{digest} — the file "
                    f"was corrupted (bit-flip/partial overwrite) after "
                    f"commit; restore from another committed snapshot",
                    exc=SnapshotDigestError)
    n_manifests = len([n for n in os.listdir(dirname)
                       if n.startswith("manifest-")
                       and n.endswith(".json")])
    want = int(record.get("manifests", n_manifests))
    enforce(n_manifests == want,
            f"snapshot dir {dirname!r} holds {n_manifests} manifest(s) "
            f"but the {COMMIT_MARKER} marker recorded {want} — shard "
            f"files from another world mixed in?",
            exc=InvalidArgumentError)


def _resolve_snapshot_dir(path: str) -> str:
    """Accept either a snapshot dir or a root of snapshot-* dirs."""
    if os.path.basename(os.path.normpath(path)).startswith(SNAPSHOT_PREFIX):
        return path
    if os.path.isdir(path) and any(
            _SNAP_RE.match(n) for n in os.listdir(path)):
        latest = latest_snapshot(path)
        enforce(latest is not None,
                f"checkpoint root {path!r} holds snapshot dirs but none "
                f"is committed (no {COMMIT_MARKER} markers) — every save "
                f"was interrupted before its commit point",
                exc=NotFoundError)
        return latest
    return path


# ---------------------------------------------------------------------------
# train-state metadata
# ---------------------------------------------------------------------------

def _strategy_dict(strategy) -> Dict[str, Any]:
    if strategy is None:
        return {}
    from .strategy import ReduceStrategy
    return {
        "reduce_strategy": ReduceStrategy(strategy.reduce_strategy).name,
        "quant_comm": strategy.quant_comm,
        "quant_comm_block": strategy.quant_comm_block,
        "comm_error_feedback": strategy.comm_error_feedback,
        "comm_bucket_bytes": strategy.comm_bucket_bytes,
        "pipeline_stages": strategy.pipeline_stages,
        "num_microbatches": strategy.num_microbatches,
        "pipeline_schedule": strategy.pipeline_schedule,
    }


def _ef_layout(program) -> Optional[Dict[str, Any]]:
    """The error-feedback transfer layout of a comm-rewritten program:
    which grads ride which transfer, in which order, at which flat
    sizes — everything `_remap_error_feedback` needs to re-map residual
    state onto a DIFFERENT dp world (var names and row counts both
    change with dp)."""
    if not getattr(program, "_dp_comm_applied", False):
        return None
    block = program.global_block()
    comm = next((op for op in block.ops if op.type == "dp_grad_comm"), None)
    if comm is None or not comm.attrs.get("error_feedback"):
        return None
    err_names = list(comm.inputs.get("ErrIn", []))
    if not err_names:
        return None
    kinds = comm.attrs["kinds"]
    numels = comm.attrs["numels"]
    grads = list(comm.inputs["X"])
    dp = int(comm.attrs["dp"])
    tp = int(getattr(program, "_tp_size", 0) or 0) \
        if getattr(program, "_tp_applied", False) else 0

    def _grad_geometry(gname):
        """(global shape, tp-sharded dim index or None) of a gradient —
        what lets the restore re-map a residual segment through the
        GLOBAL gradient space when the tp degree changes across a
        resize. The comm plan's numels are tp-LOCAL; the grad var's
        declared shape is global, its `tp_spec` (tp_shard_pass marker)
        names the dim the tp axis splits."""
        from ..framework.sharding import tp_component
        g = block.var(gname)
        gshape = list(g.shape or ())
        comp = tp_component(getattr(g, "tp_spec", None)) if tp > 1 \
            else None
        tp_dim = None
        if comp is not None:
            dims = [d for d, s in enumerate(comp) if s is not None]
            enforce(len(dims) == 1,
                    f"gradient {gname!r} is tp-sharded on {len(dims)} "
                    f"dims — the error-feedback resize re-map supports "
                    f"single-dim tp sharding", exc=InvalidArgumentError)
            tp_dim = dims[0]
        return gshape, tp_dim

    transfers = []
    # the pass lays err state out sharded-transfers-first, then buckets —
    # mirror that order (grad_comm.py _comm_optimize_pass_impl)
    for i, kind in enumerate(kinds):
        if kind == "sharded":
            gshape, tp_dim = _grad_geometry(grads[i])
            transfers.append({"kind": "sharded", "grads": [grads[i]],
                              "numels": [numels[i]], "flat": numels[i],
                              "gshapes": [gshape], "tp_dims": [tp_dim]})
    for idxs in comm.attrs["buckets"]:
        flat = sum(numels[i] for i in idxs)
        geo = [_grad_geometry(grads[i]) for i in idxs]
        transfers.append({"kind": "bucket",
                          "grads": [grads[i] for i in idxs],
                          "numels": [numels[i] for i in idxs],
                          "flat": -(-flat // dp) * dp,
                          "gshapes": [g for g, _ in geo],
                          "tp_dims": [d for _, d in geo]})
    enforce(len(transfers) == len(err_names),
            f"error-feedback layout mismatch: {len(transfers)} transfers "
            f"vs {len(err_names)} state vars", exc=InvalidArgumentError)
    for t, name in zip(transfers, err_names):
        t["var"] = name
    return {"dp": dp, "tp": max(tp, 1),
            "quant": comm.attrs["quant"], "block": comm.attrs["block"],
            "transfers": transfers}


# ---------------------------------------------------------------------------
# save
# ---------------------------------------------------------------------------

_PENDING: List["AsyncSnapshot"] = []
_pending_lock = threading.Lock()
_serial_lock = threading.Lock()
_last_serial = -1

# host bytes currently staged for in-flight snapshot writes (the d2h
# copies a background writer still holds) ride the SHARED pinned host
# pool ledger (framework/offload.py, category "staging") — the
# host_staging_bytes watermark, the census host-tier rows, and /healthz
# all read the same accounting source as the KV spill and optimizer
# tiers (ISSUE r23 satellite 6), so concurrent consumers sum instead of
# double-reporting and the pool's peak records the worst co-residency.


def _note_staging(delta: float):
    from ..framework import offload as _offload
    # the pool's lock computes the total AND publishes the watermark in
    # one critical section: two writers finishing together publish in
    # total order, so the channel's "current" cannot stick stale
    _offload.shared_host_pool()._credit("staging", int(delta))


def _chunk_nbytes(chunks) -> float:
    return float(sum(getattr(a, "nbytes", 0) for a in chunks.values()))


def _alloc_serial(root: str) -> int:
    """Monotone snapshot serial: max(disk, in-process counter) under a
    lock, so two async saves racing before either's directory exists
    cannot mint the same serial (their staging dirs would collide and
    the second rename would clobber the first commit)."""
    global _last_serial
    with _serial_lock:
        snaps = list_snapshots(root, committed_only=False)
        serial = max(_last_serial + 1,
                     (snaps[-1][0] + 1) if snaps else 0)
        _last_serial = serial
        return serial


class AsyncSnapshot:
    """Handle for a background snapshot write. The device→host copy
    already happened when this handle exists — the training loop may
    mutate state freely. result() blocks until the commit (re-raising
    any writer exception) and returns the committed snapshot path."""

    def __init__(self, serial: Optional[int] = None):
        self._event = threading.Event()
        self._path: Optional[str] = None
        self._exc: Optional[BaseException] = None
        self._serial = serial

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> str:
        if not self._event.wait(timeout):
            raise TimeoutError("snapshot write not committed in time")
        if self._exc is not None:
            raise self._exc
        return self._path

    def _finish(self, path=None, exc=None):
        self._path = path
        self._exc = exc
        with _pending_lock:
            if self in _PENDING:
                _PENDING.remove(self)
        self._event.set()


def wait_for_pending(timeout: Optional[float] = None):
    """Block until every in-flight async snapshot committed — the drain
    hook (EngineServer SIGTERM drain, supervisor shutdown, end of
    training) that guarantees no writer thread is still holding dirty
    state when the process exits."""
    with _pending_lock:
        pending = list(_PENDING)
    for h in pending:
        h.result(timeout)


def _collect_train_arrays(program, scope) -> Dict[str, object]:
    from ..io import _is_persistable, _select_vars
    arrays = {}
    for v in _select_vars(program, _is_persistable):
        if scope.has_var(v.name):
            arrays[v.name] = scope.get(v.name)
    enforce(arrays, "no persistable state in scope — run the startup "
            "program before snapshotting", exc=InvalidArgumentError)
    return arrays


def _placements(arrays: Dict[str, object]) -> Dict[str, Any]:
    """Per-var partition spec of the LIVE arrays at save time, recorded
    in train_meta.json — the `old placement` side of the mesh-resize
    planner (parallel/reshard.py): which mesh axes shard which dim.
    Host arrays (no sharding) record null."""
    out = {}
    for name, arr in arrays.items():
        sh = getattr(arr, "sharding", None)
        spec = getattr(sh, "spec", None)
        if spec is None:
            out[name] = None
            continue
        entry = []
        for s in spec:
            if s is None:
                entry.append(None)
            elif isinstance(s, (tuple, list)):
                entry.append(list(s))
            else:
                entry.append([s])
        out[name] = entry
    return out


def _prepared_view(executor, program, scope):
    """The program AS THE EXECUTOR RUNS IT: ParallelExecutor rewrites
    (tp/dp-comm/pipeline) before compiling, and checkpoint contents +
    placement policy must follow the REWRITTEN view (sharded
    accumulators, error-feedback vars)."""
    if executor is not None and hasattr(executor, "prepare_program"):
        return executor.prepare_program(program, scope)
    return program


def save_train_state(root: str,
                     program=None, scope=None, executor=None,
                     step: int = 0, extra_meta: Optional[dict] = None,
                     max_snapshots: int = 3,
                     block: bool = True,
                     world=None,
                     barrier_deadline_s: float = 60.0):
    """Snapshot the complete training state under `root` with the atomic
    two-phase commit. Returns the committed snapshot path (block=True)
    or an AsyncSnapshot handle (block=False: only the device→host copy
    happens on the caller's thread; a background writer does the file
    writes + commit off the step critical path).

    `executor` is the executor DRIVING training (Executor or
    ParallelExecutor): its run counter — the RNG seed stream position —
    rides the metadata, so a restored run draws exactly the seeds the
    uninterrupted run would have. ParallelExecutor additionally
    contributes its BuildStrategy/mesh config and the rewritten program
    view (sharded accumulators, error-feedback state).

    `world` (a process_world.ProcessWorld) switches to the MULTI-WRITER
    chief-commits barrier: the mesh's devices are partitioned across the
    world's ranks, every rank stages + fsyncs its OWN shard files in a
    rank-private directory and reports a per-file digest manifest to the
    chief, and the chief — after collecting every live rank's ack within
    `barrier_deadline_s` — binds all of them into ONE COMMIT record
    whose atomic rename is the only thing that makes the snapshot
    visible. A straggler past the deadline or a dead rank ABORTS the
    snapshot (returns None / AsyncSnapshot.result() -> None; counted in
    ptpu_ckpt_barrier_aborts_total) and training continues."""
    import jax

    from ..framework.program import default_main_program
    from ..framework.scope import global_scope
    from ..observability import tracing as _tracing
    from ..sharded_checkpoint import collect_chunks

    # BOTH paths are single-OS-process protocols today: the single-writer
    # path because rmtree-leftovers + rename + retention assume one owner
    # of the root, and the ProcessWorld barrier because its ranks are
    # SIMULATED in-process (rank staging dirs carry no pid; two real OS
    # processes passing worlds would mint one serial and clobber each
    # other's rank staging — exactly the silent checkpoint loss this
    # enforce exists to reject). On a real jax.distributed deployment the
    # rank surface transplants onto actual processes; until then, reject.
    enforce(jax.process_count() == 1,
            f"elastic save_train_state runs in one OS process today "
            f"(process_count={jax.process_count()}): the ProcessWorld "
            f"barrier simulates its ranks in-process, and concurrent "
            f"REAL processes would overwrite each other's snapshot "
            f"serials and rank staging. Use "
            f"trainer.save_checkpoint(sharded=True) for real multi-host "
            f"saves", exc=InvalidArgumentError)
    program = program or default_main_program()
    scope = scope or global_scope()
    prepared = _prepared_view(executor, program, scope)
    arrays = _collect_train_arrays(prepared, scope)

    mesh = getattr(executor, "mesh", None)
    strategy = getattr(executor, "build_strategy", None)
    meta = {
        "format": META_FORMAT,
        "step": int(step),
        "run_counter": int(getattr(executor, "_run_counter", 0) or 0),
        "random_seed": int(program.random_seed),
        "world": dict(getattr(mesh, "axes", {}) or {}),
        "world_size": world.world_size if world is not None else 1,
        "strategy": _strategy_dict(strategy),
        "ef_layout": _ef_layout(prepared),
        "placements": _placements(arrays),
        "extra": dict(extra_meta or {}),
        "var_names": sorted(arrays),
    }

    if world is not None:
        with _tracing.span("checkpoint", "elastic/snapshot_d2h",
                           n_vars=len(arrays), step=int(step),
                           world_size=world.world_size):
            rank_payloads = _collect_rank_chunks(world, arrays, mesh)
        staged = sum(_chunk_nbytes(c) for c, _ in rank_payloads.values())
        os.makedirs(root, exist_ok=True)
        serial = _alloc_serial(root)
        # note the staged bytes only once every step that can raise
        # OUTSIDE a try/finally is behind us (an unwritable root must
        # not leave the watermark permanently inflated)
        _note_staging(staged)
        if block:
            try:
                return _barrier_write_and_commit(
                    world, root, serial, rank_payloads, meta,
                    max_snapshots, step, barrier_deadline_s)
            finally:
                _note_staging(-staged)
        handle = AsyncSnapshot(serial)
        with _pending_lock:
            _PENDING.append(handle)

        def _bwriter():
            try:
                path = _barrier_write_and_commit(
                    world, root, serial, rank_payloads, meta,
                    max_snapshots, step, barrier_deadline_s)
                handle._finish(path=path)
            except BaseException as e:  # noqa: BLE001 - via result()
                handle._finish(exc=e)
            finally:
                _note_staging(-staged)

        t = threading.Thread(target=_bwriter,
                             name=f"ckpt-barrier-{serial}", daemon=True)
        t.start()
        return handle

    with _tracing.span("checkpoint", "elastic/snapshot_d2h",
                       n_vars=len(arrays), step=int(step)):
        chunks, manifest, pid = collect_chunks(arrays)
    staged = _chunk_nbytes(chunks)

    os.makedirs(root, exist_ok=True)
    serial = _alloc_serial(root)
    final = os.path.join(root, f"{SNAPSHOT_PREFIX}{serial:08d}")
    staging = os.path.join(root,
                           f"{STAGING_PREFIX}{serial:08d}-{os.getpid()}")
    # see the barrier path: only note once the can-raise setup is done,
    # so the compensating decrement in the finally always runs
    _note_staging(staged)

    if block:
        try:
            return _write_and_commit(staging, final, chunks, manifest,
                                     pid, meta, root, max_snapshots,
                                     step, serial)
        finally:
            _note_staging(-staged)
    handle = AsyncSnapshot(serial)
    with _pending_lock:
        _PENDING.append(handle)

    def _writer():
        try:
            path = _write_and_commit(staging, final, chunks, manifest,
                                     pid, meta, root, max_snapshots,
                                     step, serial)
            handle._finish(path=path)
        except BaseException as e:  # noqa: BLE001 - surfaced via result()
            handle._finish(exc=e)
        finally:
            _note_staging(-staged)

    t = threading.Thread(target=_writer, name=f"ckpt-writer-{serial}",
                         daemon=True)
    t.start()
    return handle


def _stage_digests(staging: str) -> Dict[str, dict]:
    """Per-file {size, crc32} integrity entries for a staging dir's
    payload. The digest re-reads the just-written files: page-cache-hot,
    so it is a memory-speed pass rather than a second disk round trip,
    and hashing the on-disk container bytes keeps the digest's meaning
    independent of the writer's serialization internals (a streamed
    in-memory hash would silently diverge from disk if the container
    format ever buffered/reordered)."""
    return {n: {"size": os.path.getsize(os.path.join(staging, n)),
                "crc32": file_digest(os.path.join(staging, n))}
            for n in _payload_files(staging)}


def _commit_marker_and_retain(root: str, final: str, files: Dict,
                              n_manifests: int, step: int,
                              world_info: Dict, max_snapshots: int):
    """THE commit point, shared by the single-writer save and the
    chief's barrier commit so the COMMIT record format and the
    retention rule exist exactly once: write the integrity record to a
    temp name, fsync, atomically rename it in, fsync the dir, then
    prune retention by the SAME (step, commit_ts, serial) ranking
    selection uses — a stale writer minting later serials at earlier
    steps must never push the newest-step snapshot out of retention."""
    from ..sharded_checkpoint import _fsync_file
    marker = os.path.join(final, COMMIT_MARKER)
    with open(marker + ".tmp", "w") as f:
        json.dump({"format": COMMIT_FORMAT, "manifests": n_manifests,
                   "files": files, "step": int(step),
                   "commit_ts": time.time(), "world": world_info}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(marker + ".tmp", marker)
    _fsync_file(final)
    if max_snapshots and max_snapshots > 0:
        for old in _ranked_snapshots(root)[:-max_snapshots]:
            shutil.rmtree(old, ignore_errors=True)


def _write_and_commit(staging, final, chunks, manifest, pid, meta,
                      root, max_snapshots, step, serial) -> str:
    """Phase 2: staged writes, fsync, rename, COMMIT marker, retention.
    The fault-injection crash points live here (see
    fault_injection_config)."""
    from ..observability import tracing as _tracing
    from ..sharded_checkpoint import _fsync_file, write_chunks

    fault = fault_injection_config()
    slow = fault.get("slow_writer")
    if slow:
        time.sleep(float(slow))
    t0 = time.perf_counter()
    with _tracing.span("checkpoint", "elastic/snapshot_write",
                       step=int(step)):
        if os.path.isdir(staging):
            shutil.rmtree(staging)
        os.makedirs(staging)
        write_chunks(staging, chunks, manifest, pid, fsync=True)
        meta_path = os.path.join(staging, META_FILE)
        with open(meta_path, "w") as f:
            json.dump(meta, f, indent=1)
            f.flush()
            os.fsync(f.fileno())

        mid = fault.get("crash_mid_save")
        if mid is not None:
            _crash_mid_staging(staging, int(mid))  # may not return
        payload = _stage_digests(staging)
        n_manifests = len([n for n in payload if n.startswith("manifest-")])

    with _tracing.span("checkpoint", "elastic/commit", step=int(step)):
        if os.path.isdir(final):
            # leftovers of a preempted save that never committed (a
            # COMMITTED dir at this serial is impossible: the serial scan
            # above counted it)
            shutil.rmtree(final)
        os.replace(staging, final)
        _fsync_file(root)
        payload_bytes = sum(e["size"] for e in payload.values())
        if mid is not None and int(mid) == payload_bytes:
            # crash point "after rename, before COMMIT": the snapshot dir
            # is visible but uncommitted — restore must skip it
            _sigkill_self()  # pragma: no cover
        _commit_marker_and_retain(
            root, final, payload, n_manifests, step,
            {"world_size": meta.get("world_size", 1),
             "axes": meta.get("world", {})}, max_snapshots)
    if mid is not None and int(mid) > payload_bytes:
        _sigkill_self()  # pragma: no cover

    # sweep stale SINGLE-WRITER staging dirs (.tmp-<serial>-<pid>) from
    # earlier preempted/dead saves — never one a LIVE async writer of
    # this process still owns (its serial is >= the oldest pending
    # serial), and never the barrier protocol's -rank<r>/-world<pid>
    # dirs, whose rounds are not tracked in _PENDING (blocking barrier
    # saves) and are swept by the barrier's own commit
    with _pending_lock:
        live = {h._serial for h in _PENDING if h._serial is not None}
    floor = min(live | {serial})
    stale_re = re.compile(re.escape(STAGING_PREFIX) + r"(\d+)-(\d+)$")
    for name in os.listdir(root):
        m = stale_re.match(name)
        if m and int(m.group(1)) < floor and \
                os.path.join(root, name) != staging:
            shutil.rmtree(os.path.join(root, name), ignore_errors=True)

    dt = time.perf_counter() - t0
    _metric("ptpu_ckpt_saves_total").inc()
    _metric("ptpu_ckpt_save_bytes_total").inc(payload_bytes)
    _metric("ptpu_ckpt_save_seconds").observe(dt)
    flags.vlog(1, "committed snapshot %s (%d bytes, %.3fs)", final,
               payload_bytes, dt)
    return final


# ---------------------------------------------------------------------------
# chief-commits multi-writer barrier (over a simulated ProcessWorld)
# ---------------------------------------------------------------------------

def _collect_rank_chunks(world, arrays: Dict[str, object], mesh):
    """The per-rank device→host phase of a multi-writer save: partition
    the mesh's devices into world_size contiguous groups and collect,
    per rank, ONLY the chunks whose replica-0 shard lives on that rank's
    devices (sharded_checkpoint.collect_chunks only_devices — in a real
    multi-host world `addressable_shards` IS this split). Host arrays
    (no device placement) are written by the chief alone. Returns
    {rank: (chunks, manifest)}."""
    from ..sharded_checkpoint import collect_chunks

    enforce(mesh is not None,
            "a multi-writer save needs the executor's mesh to partition "
            "device ownership across ranks", exc=InvalidArgumentError)
    devices = list(mesh.jax_mesh.devices.flat)
    n = world.world_size
    enforce(len(devices) % n == 0,
            f"mesh has {len(devices)} device(s), not divisible by "
            f"world_size={n}: every rank must own an equal device group",
            exc=InvalidArgumentError)
    per = len(devices) // n
    device_arrays = {k: v for k, v in arrays.items()
                     if hasattr(v, "addressable_shards")}
    host_arrays = {k: v for k, v in arrays.items()
                   if not hasattr(v, "addressable_shards")}
    payloads = {}
    for r in range(n):
        group = set(devices[r * per:(r + 1) * per])
        rank_arrays = dict(device_arrays)
        if world.is_chief(r):
            rank_arrays.update(host_arrays)
        chunks, manifest, _ = collect_chunks(
            rank_arrays, process_index=r, world_size=n,
            only_devices=group)
        payloads[r] = (chunks, manifest)
    return payloads


def _rank_staging_dir(root: str, serial: int, rank: int) -> str:
    return os.path.join(root, f"{STAGING_PREFIX}{serial:08d}-rank{rank}")


def _stage_rank_files(world, root: str, serial: int, rank: int,
                      chunks, manifest) -> Dict[str, dict]:
    """Phase `stage` + `ack` of one rank: write this rank's shard
    container + manifest into its RANK-PRIVATE staging directory, fsync
    everything, then build the per-file digest manifest the ack carries.
    The two fault points bracket exactly the states the crash matrix
    needs: died mid-write (possibly at a byte offset) vs staged-durable-
    but-ack-unsent."""
    from ..observability import tracing as _tracing
    from ..sharded_checkpoint import write_chunks

    staging = _rank_staging_dir(root, serial, rank)
    with _tracing.span("checkpoint", "barrier/stage", rank=rank,
                       serial=serial):
        if os.path.isdir(staging):
            shutil.rmtree(staging)
        os.makedirs(staging)
        write_chunks(staging, chunks, manifest, rank, fsync=True)
        world.fault(rank, "stage", staging=staging, serial=serial)
        digests = _stage_digests(staging)
    world.fault(rank, "ack", serial=serial)
    return digests


def _chief_commit(world, root: str, serial: int, own_files: Dict,
                  expected: List[int], meta: Dict, max_snapshots: int,
                  step: int, deadline_s: float) -> Optional[str]:
    """The chief's half of the barrier: collect every expected rank's
    digest ack within the deadline, then make the ensemble atomic —
    assemble every rank's staged files into one directory, write the
    train metadata, rename the directory into place, and only then
    atomically rename in the ONE global COMMIT record binding every
    rank's manifest. Any rank missing at the deadline aborts the
    snapshot (training continues; the attempt's staging is swept).
    Returns (committed path, payload bytes) — (None, 0) on abort."""
    from ..observability import tracing as _tracing
    from ..sharded_checkpoint import _fsync_file

    chief = world.chief
    acks: Dict[int, Dict] = {chief: own_files}
    t_wait = time.perf_counter()
    deadline = time.monotonic() + deadline_s
    while set(acks) < set(expected):
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        msg = world.recv(chief, timeout=remaining)
        if msg is None:
            break
        if (msg.get("kind") == "ack"
                and int(msg.get("serial", -1)) == serial):
            acks[int(msg["rank"])] = msg["files"]
    # the chief's wait-for-acks window as a span: its duration IS the
    # straggler gap a merged timeline shows the chief blocked on
    _tracing.record_span("checkpoint", "barrier/collect_acks",
                         t_wait, time.perf_counter(), rank=chief,
                         serial=serial, acked=sorted(acks))

    missing = sorted(set(expected) - set(acks))
    if missing:
        flags.vlog(0, "barrier abort: snapshot serial %d missing ack(s) "
                   "from rank(s) %s after %.1fs deadline — training "
                   "continues without this snapshot", serial, missing,
                   deadline_s)
        _metric("ptpu_ckpt_barrier_aborts_total").inc()
        # sweep only ACKED ranks' staging: a missing rank may be a
        # straggler STILL writing its private dir — it cleans up itself
        # on the abort verdict, or the next commit's stale sweep does
        for r in acks:
            shutil.rmtree(_rank_staging_dir(root, serial, r),
                          ignore_errors=True)
        for r in range(world.world_size):
            if r != chief:
                world.send(chief, r, "abort", serial=serial)
        return None, 0

    # every live rank's shards are durable on disk — the commit point
    world.fault(chief, "barrier", serial=serial)
    t_commit = time.perf_counter()
    assembly = os.path.join(
        root, f"{STAGING_PREFIX}{serial:08d}-world{os.getpid()}")
    if os.path.isdir(assembly):
        shutil.rmtree(assembly)
    os.makedirs(assembly)
    files: Dict[str, dict] = {}
    for r, rank_files in sorted(acks.items()):
        staging = _rank_staging_dir(root, serial, r)
        for name, entry in rank_files.items():
            enforce(name not in files,
                    f"barrier commit: rank {r} staged {name!r} which "
                    f"another rank already owns — rank file namespaces "
                    f"must be disjoint", exc=InvalidArgumentError)
            os.replace(os.path.join(staging, name),
                       os.path.join(assembly, name))
            files[name] = entry
        shutil.rmtree(staging, ignore_errors=True)
    meta_path = os.path.join(assembly, META_FILE)
    with open(meta_path, "w") as f:
        json.dump(meta, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    files[META_FILE] = {"size": os.path.getsize(meta_path),
                        "crc32": file_digest(meta_path)}
    _fsync_file(assembly)

    final = os.path.join(root, f"{SNAPSHOT_PREFIX}{serial:08d}")
    if os.path.isdir(final):
        # leftovers of a preempted attempt that never committed (a
        # COMMITTED dir at this serial is impossible: _alloc_serial
        # scanned past it)
        shutil.rmtree(final)
    os.replace(assembly, final)
    _fsync_file(root)
    world.fault(chief, "commit", serial=serial)
    n_manifests = len([n for n in files if n.startswith("manifest-")])
    _commit_marker_and_retain(
        root, final, files, n_manifests, step,
        {"world_size": world.world_size, "axes": meta.get("world", {})},
        max_snapshots)
    world.fault(chief, "post", serial=serial)
    _tracing.record_span("checkpoint", "barrier/commit", t_commit,
                         time.perf_counter(), rank=chief, serial=serial)

    # sweep staging leftovers of EARLIER barrier rounds (aborted or
    # crashed attempts); rounds are serialized on world.barrier_lock, so
    # a lower serial can never belong to a live writer
    stale_re = re.compile(re.escape(STAGING_PREFIX)
                          + r"(\d+)-(?:rank\d+|world\d+)$")
    for name in os.listdir(root):
        m = stale_re.match(name)
        if m and int(m.group(1)) < serial:
            shutil.rmtree(os.path.join(root, name), ignore_errors=True)
    for r in range(world.world_size):
        if r != chief:
            world.send(chief, r, "committed", serial=serial, path=final)
    return final, sum(e["size"] for e in files.values())


def _barrier_write_and_commit(world, root: str, serial: int,
                              rank_payloads: Dict, meta: Dict,
                              max_snapshots: int, step: int,
                              deadline_s: float) -> Optional[str]:
    """Run the chief-commits barrier over the world: every rank stages
    and acks; the chief waits, binds, and commits. Returns the committed
    path, or None when the barrier aborted (straggler/dead rank)."""
    from ..observability import flight_recorder as _fr
    from ..observability import tracing as _tracing

    fault = fault_injection_config()
    slow = fault.get("slow_writer")
    if slow:
        time.sleep(float(slow))
    if world.dead:
        # a dead rank can never stage its shard of the state, so no
        # COMPLETE snapshot can commit in this world again: fail fast
        # instead of letting the live ranks stage and time out. The
        # recovery is a whole-gang restart (Supervisor world_size
        # semantics), not a partial commit.
        flags.vlog(0, "barrier abort: rank(s) %s are dead — no complete "
                   "snapshot can commit in this world; restart the gang",
                   sorted(world.dead))
        _metric("ptpu_ckpt_barrier_aborts_total").inc()
        return None
    t0 = time.perf_counter()
    committed_bytes: List[int] = []   # filled by the chief on commit

    def rank_fn(rank: int):
        chunks, manifest = rank_payloads[rank]
        if world.is_chief(rank):
            # the whole chief branch — its OWN staging included — is
            # wrapped: a chief dying at ANY phase (stage/ack/barrier/
            # commit) must not leave the other ranks blocked on a
            # verdict that will never come, and the abort must be
            # visible in the metrics
            try:
                digests = _stage_rank_files(world, root, serial, rank,
                                            chunks, manifest)
                path, nbytes = _chief_commit(world, root, serial,
                                             digests, expected, meta,
                                             max_snapshots, step,
                                             deadline_s)
                committed_bytes.append(nbytes)
                return path
            except BaseException:
                _metric("ptpu_ckpt_barrier_aborts_total").inc()
                for r in range(world.world_size):
                    if r != rank:
                        world.send(rank, r, "abort", serial=serial)
                raise
        digests = _stage_rank_files(world, root, serial, rank, chunks,
                                    manifest)
        world.send(rank, world.chief, "ack", serial=serial, rank=rank,
                   files=digests)
        # wait for the chief's verdict; a silent timeout (chief dead)
        # counts as an abort from this rank's perspective
        limit = time.monotonic() + deadline_s + 30.0
        while True:
            msg = world.recv(rank, timeout=max(0.1,
                                               limit - time.monotonic()))
            if msg is None and time.monotonic() >= limit:
                return None
            if msg and int(msg.get("serial", -1)) == serial:
                if msg["kind"] == "committed":
                    return msg["path"]
                if msg["kind"] == "abort":
                    shutil.rmtree(_rank_staging_dir(root, serial, rank),
                                  ignore_errors=True)
                    return None

    # the state board names the ACTIVE barrier round: a dossier dumped
    # while this round runs (rank death, enforce error) records which
    # serial/step was in flight
    _fr.set_state("barrier", serial=serial, step=int(step),
                  world_size=world.world_size, world=world.world_id,
                  status="running")
    with _tracing.span("checkpoint", "elastic/barrier_commit",
                       step=int(step), world_size=world.world_size), \
            world.barrier_lock:
        # EVERY rank's shard is needed for a complete snapshot: a rank
        # dying mid-round surfaces as a missing ack -> abort
        expected = list(range(world.world_size))
        world.drain(world.chief)  # no stale acks from an aborted round
        results = world.run(rank_fn)
    path = results[world.chief]
    _fr.set_state("barrier", serial=serial,
                  status="committed" if path is not None else "aborted")
    if path is not None:
        dt = time.perf_counter() - t0
        nbytes = committed_bytes[0] if committed_bytes else 0
        _metric("ptpu_ckpt_saves_total").inc()
        _metric("ptpu_ckpt_save_bytes_total").inc(nbytes)
        _metric("ptpu_ckpt_save_seconds").observe(dt)
        flags.vlog(1, "barrier-committed snapshot %s (%d ranks, %d "
                   "bytes, %.3fs)", path, len(expected), nbytes, dt)
    return path


# ---------------------------------------------------------------------------
# error-feedback N→M re-mapping
# ---------------------------------------------------------------------------

def _resize_replica_rows(rows: np.ndarray, new_n: int) -> np.ndarray:
    """Re-map per-replica residual rows [N, n] onto M replicas while
    preserving the EFFECTIVE pending gradient: each step applies
    mean_i(g_i + e_i), so the pending correction is (1/N)·Σe — rows are
    scaled by M/N so (1/M)·Σe' == (1/N)·Σe exactly. Growing pads zero
    rows (new replicas start with no residual); shrinking folds rows
    modulo M. pad-then-fold is the identity, so an N→M→N round trip with
    M ≥ N restores the original rows bit-exactly when M/N is a power of
    two (f32 scaling by powers of two is exact)."""
    n_old = rows.shape[0]
    scale = np.float32(new_n) / np.float32(n_old)
    out = np.zeros((new_n,) + rows.shape[1:], rows.dtype)
    if new_n >= n_old:
        out[:n_old] = rows
    else:
        for i in range(n_old):
            out[i % new_n] += rows[i]
    return (out * scale).astype(rows.dtype)


def _remap_error_feedback(ckpt, old_layout: Dict, new_layout: Dict,
                          new_dp: int) -> Dict[str, np.ndarray]:
    """Saved residual state (old transfer layout, old dp×tp rows) → host
    arrays for the NEW layout's error-feedback vars (new dp×tp rows),
    across an ARBITRARY mesh resize of the dp and tp axes.

    Per-gradient segments are extracted from the old flat vectors; dp
    replica rows re-map via `_resize_replica_rows` (grow pads zero rows,
    shrink folds mod M, scaled M/N so the effective pending correction
    (1/N)·Σe is preserved); gradients may move between transfers when
    their dp/quant-block classification changes with the resize. Bucket
    pad regions carry an identically zero residual (quantizing an exact
    zero leaves no residual), so dropping/re-padding them is lossless.

    The tp axis: when the tp degree is UNCHANGED, every (tp, dp)
    coordinate's rows re-map independently — a same-world restore is
    bitwise. When it CHANGES, segments travel through the gradient's
    GLOBAL coordinate space (`ef_layout` gshapes/tp_dims): a tp-sharded
    gradient's per-shard segments reassemble along the recorded tp dim
    and re-slice into the new degree's locals EXACTLY; a gradient
    replicated over tp collapses to the MEAN of its per-shard rows and
    broadcasts to the new shards — per-shard residuals legitimately
    differ there (quantization scale blocks span neighboring tp-local
    bucket segments), so no bijection exists across a tp change and the
    mean is the unbiased mass-preserving choice, off from any single
    shard's rows by at most the wire-format quantization noise."""
    enforce((old_layout["quant"], old_layout["block"])
            == (new_layout["quant"], new_layout["block"]),
            f"error-feedback state is only meaningful under the wire "
            f"config that produced it: checkpoint quant="
            f"{old_layout['quant']!r}/block={old_layout['block']} vs "
            f"target {new_layout['quant']!r}/{new_layout['block']} — "
            f"restore with the same quant_comm config, or drop "
            f"comm_error_feedback to start residuals at zero",
            exc=InvalidArgumentError)
    old_tp = int(old_layout["tp"])
    new_tp = int(new_layout["tp"])
    old_dp = int(old_layout["dp"])
    tp_resize = old_tp != new_tp
    enforce(not tp_resize or all("gshapes" in t
                                 for t in old_layout["transfers"]),
            f"elastic restore across a tp resize ({old_tp}→{new_tp}) "
            f"needs the gradient geometry in the snapshot's ef_layout — "
            f"this snapshot predates it (format 1); restore at "
            f"tp={old_tp}, or drop comm_error_feedback to start "
            f"residuals at zero", exc=InvalidArgumentError)

    # old per-grad residual segments: grad -> [old_tp, old_dp, n_local]
    per_grad: Dict[str, np.ndarray] = {}
    geometry: Dict[str, tuple] = {}   # grad -> (gshape, tp_dim)
    for t in old_layout["transfers"]:
        arr = np.asarray(ckpt.read(t["var"]))
        enforce(arr.shape == (old_dp * old_tp, t["flat"]),
                f"saved error-feedback var {t['var']!r} has shape "
                f"{arr.shape}, expected {(old_dp * old_tp, t['flat'])} — "
                f"checkpoint metadata disagrees with its contents",
                exc=InvalidArgumentError)
        arr = arr.reshape(old_tp, old_dp, t["flat"])
        gshapes = t.get("gshapes") or [None] * len(t["grads"])
        tp_dims = t.get("tp_dims") or [None] * len(t["grads"])
        off = 0
        for g, n, gshape, tp_dim in zip(t["grads"], t["numels"],
                                        gshapes, tp_dims):
            per_grad[g] = arr[:, :, off:off + n]
            geometry[g] = (gshape, tp_dim)
            off += n

    def _to_global(g, seg):
        """[old_tp, old_dp, n] -> [old_dp, *gshape] (tp resize only)."""
        gshape, tp_dim = geometry[g]
        if tp_dim is None or old_tp == 1:
            # replicated over tp: collapse to the per-shard mean (see
            # docstring — no bijection exists; the mean preserves the
            # average pending correction)
            return seg.mean(axis=0).reshape((old_dp,) + tuple(gshape))
        loc = list(gshape)
        enforce(loc[tp_dim] % old_tp == 0,
                f"gradient {g!r} dim {tp_dim} ({loc[tp_dim]}) does not "
                f"divide over tp={old_tp}", exc=InvalidArgumentError)
        loc[tp_dim] //= old_tp
        parts = [seg[ti].reshape((old_dp,) + tuple(loc))
                 for ti in range(old_tp)]
        return np.concatenate(parts, axis=1 + tp_dim)

    out: Dict[str, np.ndarray] = {}
    for t in new_layout["transfers"]:
        new = np.zeros((new_tp, new_dp, t["flat"]), np.float32)
        gshapes = t.get("gshapes") or [None] * len(t["grads"])
        tp_dims = t.get("tp_dims") or [None] * len(t["grads"])
        off = 0
        for g, n, gshape, tp_dim in zip(t["grads"], t["numels"],
                                        gshapes, tp_dims):
            seg = per_grad.get(g)
            if seg is None:
                off += n
                continue
            if not tp_resize:
                # tp unchanged: every (tp, dp) coordinate re-maps its
                # own rows independently — same-world restores are
                # bitwise, per-shard residual identity preserved
                enforce(seg.shape[-1] == n,
                        f"gradient {g!r} changed size across the resize "
                        f"({seg.shape[-1]} vs {n}) — the checkpoint "
                        f"does not match this program",
                        exc=InvalidArgumentError)
                for ti in range(new_tp):
                    new[ti, :, off:off + n] = _resize_replica_rows(
                        seg[ti], new_dp)
                off += n
                continue
            old_gshape, _ = geometry[g]
            enforce(gshape is not None and old_gshape is not None
                    and list(old_gshape) == list(gshape),
                    f"gradient {g!r} changed global shape across the "
                    f"resize ({old_gshape and list(old_gshape)} vs "
                    f"{gshape and list(gshape)}) — the checkpoint does "
                    f"not match this program", exc=InvalidArgumentError)
            glob = _to_global(g, seg)
            resized = _resize_replica_rows(
                glob.reshape(old_dp, -1), new_dp) \
                .reshape((new_dp,) + tuple(gshape))
            if tp_dim is None or new_tp == 1:
                flat = resized.reshape(new_dp, -1)
                enforce(flat.shape[-1] == n,
                        f"gradient {g!r}: global numel {flat.shape[-1]} "
                        f"vs transfer segment {n} — tp geometry "
                        f"mismatch", exc=InvalidArgumentError)
                for ti in range(new_tp):
                    new[ti, :, off:off + n] = flat
            else:
                k = gshape[tp_dim]
                enforce(k % new_tp == 0,
                        f"gradient {g!r} dim {tp_dim} ({k}) does not "
                        f"divide over tp={new_tp}",
                        exc=InvalidArgumentError)
                step = k // new_tp
                for ti in range(new_tp):
                    idx = (slice(None),) * (1 + tp_dim) + \
                        (slice(ti * step, (ti + 1) * step),)
                    new[ti, :, off:off + n] = \
                        resized[idx].reshape(new_dp, n)
            off += n
        out[t["var"]] = new.reshape(new_tp * new_dp, t["flat"])
    return out


# ---------------------------------------------------------------------------
# restore
# ---------------------------------------------------------------------------

def read_meta(dirname: str) -> Dict[str, Any]:
    """The train_meta.json of a snapshot dir (resolves a root to its
    latest committed snapshot first). Validates sizes/commit structure
    but NOT content digests — a metadata peek must not re-hash the whole
    payload; restore_train_state runs the full digest validation before
    any state is read."""
    dirname = _resolve_snapshot_dir(dirname)
    validate_snapshot(dirname, digests=False)
    with open(os.path.join(dirname, META_FILE)) as f:
        return json.load(f)


def verify_restored_placement(executor, program, scope,
                              names=None) -> List[str]:
    """Static placement check of live state vs the executor's policy:
    for every persistable in `names` (default: all in scope), the
    array's sharding must be equivalent to what
    ParallelExecutor._state_sharding demands for this program. Returns a
    list of violation strings (empty = clean) — restore_train_state
    enforces on them; tools/lint_program.py --restore_dir reports them."""
    from ..io import _is_persistable, _select_vars
    problems = []
    if not hasattr(executor, "state_sharding"):
        return problems
    for v in _select_vars(program, _is_persistable):
        if names is not None and v.name not in names:
            continue
        if not scope.has_var(v.name):
            continue
        val = scope.get(v.name)
        sh = getattr(val, "sharding", None)
        if sh is None:
            continue
        want = executor.state_sharding(program, v.name)
        if not sh.is_equivalent_to(want, getattr(val, "ndim", 0)):
            problems.append(
                f"{v.name}: restored with {sh.spec}, executor places it "
                f"{want.spec}")
    return problems


def restore_train_state(path: str,
                        program=None, scope=None, executor=None,
                        strict: bool = True,
                        verify: bool = True,
                        replan: Optional[bool] = None) -> Dict[str, Any]:
    """Restore the latest committed snapshot under `path` (or `path`
    itself when it is a snapshot dir) into `scope`, re-placing every
    array onto the CURRENT executor's mesh — which may be an ARBITRARILY
    different dp × pp × tp world than the one that saved (dp2×tp2 → dp4,
    dp2×pp2 → dp2×tp2, ...): parameters and full-shape ZeRO-1
    accumulator chunks re-shard through make_array_from_callback (each
    device reads only the byte ranges its new slice intersects);
    error-feedback residuals re-map through `_remap_error_feedback`
    across both dp and tp changes. When the world changed, the re-layout
    is planned first (parallel/reshard.py): the per-variable collective
    redistribution schedule is emitted, cross-checked exactly against
    `framework.costs.reshard_wire_bytes`, and its summary returned as
    meta["reshard"]. Restores the executor's run counter (the RNG seed
    stream position), so a fixed-seed resumed run replays exactly the
    seeds of the uninterrupted one.

    verify=True (default) runs the r10/r13 static analyzer
    (`verify_program`) over the program as the executor rewrites it and
    checks every restored array's placement against the executor's
    policy BEFORE returning — a mis-placed restore fails here, not in
    jit's arg-sharding check mid-step.

    strict=True errors on persistables the checkpoint lacks; False
    leaves them at their startup values (warm-starting a grown model).

    replan: when the snapshot's world differs from the executor's, run
    the auto-parallel planner over the NEW world and adopt its choice
    onto the executor BEFORE re-placing any state — the planner prices
    keeping the restored strategy vs re-planning (predicted step seconds
    plus each side's one-time redistribution wire bytes, validated
    against `costs.reshard_wire_bytes`) and adopts the re-plan only when
    it wins (framework/auto_parallel.py replan_on_restore; the decision
    record returns as meta["replan"]). Default None follows the
    executor's `BuildStrategy.auto_parallel` (and the PTPU_AUTO_PARALLEL
    kill switch); True/False force it either way.

    Returns the snapshot metadata (step, extra, world, strategy...)."""
    import time as _time

    from ..framework.program import default_main_program
    from ..framework.scope import global_scope
    from ..io import _is_persistable, _select_vars
    from ..observability import tracing as _tracing
    from ..sharded_checkpoint import ShardedCheckpoint, restore_array

    t0 = _time.perf_counter()
    program = program or default_main_program()
    scope = scope or global_scope()
    dirname = _resolve_snapshot_dir(path)
    validate_snapshot(dirname)
    with open(os.path.join(dirname, META_FILE)) as f:
        meta = json.load(f)

    # re-plan BEFORE the prepared view is computed: the planner may
    # adopt a different strategy + mesh factorization onto the executor,
    # and everything below (rewritten view, EF layout, placement,
    # reshard schedule) must follow the ADOPTED configuration
    mesh = getattr(executor, "mesh", None)
    want_replan = (replan if replan is not None else bool(
        executor is not None
        and getattr(getattr(executor, "build_strategy", None),
                    "auto_parallel", False)
        and flags.get_flag("auto_parallel")))
    old_world = dict(meta.get("world", {}) or {})
    if (want_replan and executor is not None and mesh is not None
            and old_world != dict(getattr(mesh, "axes", {}) or {})):
        from ..framework import auto_parallel as _auto
        meta["replan"] = _auto.replan_on_restore(
            executor, program, scope, meta, dirname)
        mesh = executor.mesh

    prepared = _prepared_view(executor, program, scope)
    new_ef = _ef_layout(prepared)
    old_ef = meta.get("ef_layout")
    new_dp = int(mesh.axis_size("dp")) if mesh is not None else 1

    with _tracing.span("checkpoint", "elastic/restore",
                       snapshot=os.path.basename(dirname)):
        ckpt = ShardedCheckpoint(dirname)

        old_world = dict(meta.get("world", {}) or {})
        new_world = dict(getattr(mesh, "axes", {}) or {})
        if mesh is not None and old_world != new_world:
            # mesh-to-mesh resize: plan the re-layout up front — per-var
            # old coverage → new placement, the byte ranges each device
            # reads, and the equivalent on-hardware collective schedule,
            # cross-checked against the costs.py wire-byte prediction
            from . import reshard as _reshard
            plan = _reshard.plan_restore(ckpt, meta, prepared, executor)
            bad = _reshard.validate_schedule(plan)
            enforce(not bad,
                    "mesh-resize redistribution schedule does not "
                    "balance against framework.costs predictions:\n  "
                    + "\n  ".join(bad[:10]), exc=InvalidArgumentError)
            meta["reshard"] = plan.summary()
            flags.vlog(1, "mesh resize %s -> %s: %d var(s), %d moved, "
                       "%.0f wire bytes equivalent, %d bytes read",
                       old_world, new_world, len(plan.variables),
                       len(plan.moved_vars()), plan.wire_bytes,
                       plan.read_bytes)
        saved = set(ckpt.names())
        ef_vars = {t["var"] for t in (new_ef or {}).get("transfers", ())}
        restorable, missing = [], []
        for v in _select_vars(prepared, _is_persistable):
            name = v.name
            if name in ef_vars:
                continue  # handled below via the layout re-map
            if name not in saved:
                if getattr(v, "dp_replica_state", False):
                    continue  # stale EF var of another config: skip
                missing.append(name)
                continue
            restorable.append(name)
        # the strict check fires BEFORE any scope mutation: a caller that
        # catches it and falls back must not be left with exactly the
        # half-restored mixed state the error exists to prevent
        enforce(not (strict and missing),
                f"snapshot {dirname!r} lacks persistable var(s) "
                f"{missing[:8]}{'...' if len(missing) > 8 else ''} that "
                f"this program declares — restoring it would silently "
                f"mix checkpointed and freshly initialized state. Pass "
                f"strict=False to warm-start the missing vars from their "
                f"startup values", exc=InvalidArgumentError)
        for name in restorable:
            sharding = (executor.state_sharding(prepared, name)
                        if hasattr(executor, "state_sharding") else None)
            scope.set_var(name, restore_array(ckpt, name, sharding))

        if new_ef is not None:
            enforce(old_ef is not None,
                    f"this program carries error-feedback state "
                    f"(comm_error_feedback) but snapshot {dirname!r} "
                    f"recorded none — it was saved without quantized "
                    f"error feedback. Restore with the saving config, or "
                    f"disable comm_error_feedback to start residuals at "
                    f"zero", exc=InvalidArgumentError)
            import jax
            remapped = _remap_error_feedback(ckpt, old_ef, new_ef, new_dp)
            for name, host in remapped.items():
                sharding = (executor.state_sharding(prepared, name)
                            if hasattr(executor, "state_sharding")
                            else None)
                val = (jax.device_put(host, sharding)
                       if sharding is not None else host)
                scope.set_var(name, val)

    if executor is not None and "run_counter" in meta:
        executor._run_counter = int(meta["run_counter"])
    if strict and "random_seed" in meta:
        enforce(int(program.random_seed) == int(meta["random_seed"]),
                f"program.random_seed={program.random_seed} but the "
                f"snapshot was trained with random_seed="
                f"{meta['random_seed']}: the resumed seed stream would "
                f"diverge from the uninterrupted run. Rebuild the "
                f"program with the saved seed (or strict=False to accept "
                f"the divergence)", exc=InvalidArgumentError)

    if verify:
        from ..framework.analysis import verify_program
        errors = [d for d in verify_program(prepared)
                  if d.severity == "error"]
        enforce(not errors,
                "restored program failed static verification:\n  "
                + "\n  ".join(str(d) for d in errors[:10]),
                exc=InvalidArgumentError)
        problems = verify_restored_placement(executor, prepared, scope)
        enforce(not problems,
                "restored state placement disagrees with the executor's "
                "policy:\n  " + "\n  ".join(problems[:10]),
                exc=InvalidArgumentError)

    _metric("ptpu_ckpt_restores_total").inc()
    _metric("ptpu_ckpt_restore_seconds").observe(_time.perf_counter() - t0)
    return meta
