"""Elastic fault-tolerant training: atomic snapshots + preemption recovery.

Production scale means preemptible hardware (ROADMAP item 5). The
reference framework survives preemption through its parameter-server
checkpoint handler and per-trainer `_save_checkpoint` artifacts
(reference listen_and_serv_op.cc checkpoint handler + trainer.py:641);
the TPU-native reproduction checkpoints the arrays themselves — sharding
lives on them — and this module makes that crash-safe and elastic:

- `save_train_state` snapshots the COMPLETE training state: parameters,
  dp-sharded ZeRO-1 optimizer accumulators (`accumulator_of` backrefs),
  per-replica error-feedback residuals (`dp_comm_err_*`), the RNG
  seed/step counters that drive the executor's seed stream, and the
  BuildStrategy/mesh config — with an ATOMIC TWO-PHASE COMMIT: all files
  land in a hidden staging directory, every byte is fsync'd, the staging
  directory is renamed into place, and only then a `COMMIT` marker (an
  integrity record of every file and its size) is atomically renamed in.
  A kill at ANY byte offset leaves either a committed snapshot (which
  restores exactly) or an uncommitted one (which restore skips/rejects) —
  never a restorable half-write.
- the ASYNC path: the device→host copy happens synchronously at the step
  boundary (`sharded_checkpoint.collect_chunks`), then a background
  thread does the file writes and the commit, so the step critical path
  pays only the d2h copy. Every phase records a "checkpoint" span
  (observability/tracing.py) and save/restore durations + bytes land in
  this module's MetricsRegistry.
- `restore_train_state` is ELASTIC: given an executor over a DIFFERENT
  dp world (N→M replicas), each array is re-placed via
  `jax.make_array_from_callback` onto the new mesh (the r08 kill-switch
  state reconciliation, generalized across process boundaries), ZeRO-1
  optimizer slices re-shard automatically from their full-shape chunks,
  and error-feedback residuals are re-mapped N→M with the pending
  gradient mass preserved (see `_resize_replica_rows`). Before the first
  step the restored program's placement is verified statically through
  the r10/r13 analyzer (`verify_program`) and every restored array's
  sharding is checked against the executor's placement policy.
- `PTPU_FAULT_INJECT` makes preemption recovery TESTABLE: crash-at-step,
  crash-mid-save (SIGKILL at a chosen byte offset of the snapshot
  payload), slow-writer. tests/test_elastic.py and
  tools/recovery_smoke.py kill real processes through it.

Grounding (PAPERS.md): the ZeRO-1 shard layout that must round-trip is
"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training"; the N→M re-placement on restore is the checkpoint-mediated
form of "Memory-efficient array redistribution through portable
collective communication".

Directory layout (docs/fault_tolerance.md):

    <root>/
      snapshot-00000003/          committed snapshot, serial 3
        shard-0.pts               this process's chunks (tensor_store)
        manifest-0.json           chunk -> global-offset map
        train_meta.json           step/seed counters, strategy, EF layout
        COMMIT                    atomic commit marker + integrity record
      .tmp-00000004-1234/         staging dir of an interrupted save
"""

from __future__ import annotations

import json
import os
import re
import shutil
import signal
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..core import flags
from ..core.enforce import InvalidArgumentError, NotFoundError, enforce

SNAPSHOT_PREFIX = "snapshot-"
STAGING_PREFIX = ".tmp-"
COMMIT_MARKER = "COMMIT"
META_FILE = "train_meta.json"
META_FORMAT = 1


# ---------------------------------------------------------------------------
# fault injection (PTPU_FAULT_INJECT)
# ---------------------------------------------------------------------------

def fault_injection_config() -> Dict[str, float]:
    """Parse PTPU_FAULT_INJECT: comma-separated `directive:value` pairs.

      crash_at_step:<k>     SIGKILL self when maybe_crash_at_step(k) fires
      crash_mid_save:<b>    SIGKILL during the snapshot protocol at byte
                            offset b of the staged payload (b < payload:
                            truncated staging files; b == payload: after
                            the directory rename, BEFORE the COMMIT
                            marker; b > payload: just after commit)
      slow_writer:<s>       sleep s seconds in the background writer
                            before touching disk (widens the async
                            window; exercises drain paths)

    Parsed per call — tests flip the env var between runs."""
    raw = os.environ.get("PTPU_FAULT_INJECT", "")
    out: Dict[str, float] = {}
    if not raw:
        return out
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        enforce(":" in part,
                f"PTPU_FAULT_INJECT directive {part!r} must be "
                f"`name:value`", exc=InvalidArgumentError)
        name, val = part.split(":", 1)
        enforce(name in ("crash_at_step", "crash_mid_save", "slow_writer"),
                f"unknown PTPU_FAULT_INJECT directive {name!r}",
                exc=InvalidArgumentError)
        out[name] = float(val)
    return out


def _sigkill_self():  # pragma: no cover - the process dies here
    os.kill(os.getpid(), signal.SIGKILL)


def maybe_crash_at_step(step: int):
    """Training loops call this once per step: under
    `PTPU_FAULT_INJECT=crash_at_step:<k>` the process SIGKILLs itself
    when step == k — the supervisor/recovery tests' preemption."""
    cfg = fault_injection_config()
    k = cfg.get("crash_at_step")
    if k is not None and int(step) == int(k):
        flags.vlog(0, "fault injection: SIGKILL at step %d", step)
        _sigkill_self()  # pragma: no cover


def _payload_files(staging: str) -> List[str]:
    """Deterministic order of the staged payload files the
    crash_mid_save byte offset indexes into."""
    names = sorted(n for n in os.listdir(staging)
                   if n != COMMIT_MARKER and not n.endswith(".tmp"))
    return names


def _crash_mid_staging(staging: str, offset: int) -> bool:
    """crash_mid_save with offset inside the payload: make the staging
    dir look exactly as if the writer died `offset` bytes into its
    sequential write — truncate the file holding that offset, remove
    everything after it — then SIGKILL. Returns False when the offset
    lies beyond the payload (the caller crashes later in the protocol)."""
    names = _payload_files(staging)
    sizes = [os.path.getsize(os.path.join(staging, n)) for n in names]
    total = sum(sizes)
    if offset >= total:
        return False
    cum = 0
    for i, (n, sz) in enumerate(zip(names, sizes)):
        if offset < cum + sz:
            with open(os.path.join(staging, n), "r+b") as f:
                f.truncate(offset - cum)
            for later in names[i + 1:]:
                os.unlink(os.path.join(staging, later))
            break
        cum += sz
    _sigkill_self()  # pragma: no cover
    return True


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

_registry = None
_reg_lock = threading.Lock()


def metrics_registry():
    """Module-level MetricsRegistry for checkpoint telemetry: save/restore
    durations, bytes written, snapshots committed, pending async writes.
    Scrapeable alongside any other registry (observability/metrics.py)."""
    global _registry
    with _reg_lock:
        if _registry is None:
            from ..observability import metrics as m
            r = m.MetricsRegistry()
            r.counter("ptpu_ckpt_saves_total",
                      "Snapshots committed by this process.")
            r.counter("ptpu_ckpt_save_bytes_total",
                      "Payload bytes written across committed snapshots.")
            r.counter("ptpu_ckpt_restores_total", "Snapshots restored.")
            r.histogram("ptpu_ckpt_save_seconds",
                        "Wall time of the write+commit phase.",
                        buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                                 5.0, 10.0, 30.0))
            r.histogram("ptpu_ckpt_restore_seconds",
                        "Wall time of restore_train_state.",
                        buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                                 5.0, 10.0, 30.0))
            r.gauge("ptpu_ckpt_pending_async",
                    "Async snapshot writes not yet committed.",
                    fn=lambda: float(len(_PENDING)))
            _registry = r
    return _registry


def _metric(name):
    return metrics_registry().get(name)


# ---------------------------------------------------------------------------
# snapshot directory bookkeeping
# ---------------------------------------------------------------------------

_SNAP_RE = re.compile(re.escape(SNAPSHOT_PREFIX) + r"(\d+)$")


def is_committed(dirname: str) -> bool:
    return os.path.exists(os.path.join(dirname, COMMIT_MARKER))


def list_snapshots(root: str, committed_only: bool = True):
    """[(serial, path)] ascending. committed_only=True (the default —
    restore's view) skips snapshot dirs without a COMMIT marker: an
    interrupted save must never be picked as "latest"."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        m = _SNAP_RE.match(name)
        if not m:
            continue
        path = os.path.join(root, name)
        if committed_only and not is_committed(path):
            continue
        out.append((int(m.group(1)), path))
    return sorted(out)


def latest_snapshot(root: str) -> Optional[str]:
    """Path of the newest COMMITTED snapshot under root, or None."""
    snaps = list_snapshots(root, committed_only=True)
    return snaps[-1][1] if snaps else None


def validate_snapshot(dirname: str):
    """Raise a clear enforce error unless `dirname` is a complete,
    committed snapshot: COMMIT marker present and parseable, every file
    it records present at exactly the recorded size, manifest count
    matching. The property the crash-mid-save test pins: a directory
    that passes here restores exactly; one that fails is rejected with
    the directory and the missing/damaged piece named."""
    enforce(os.path.isdir(dirname),
            f"snapshot dir {dirname!r} does not exist",
            exc=NotFoundError)
    marker = os.path.join(dirname, COMMIT_MARKER)
    enforce(os.path.exists(marker),
            f"snapshot dir {dirname!r} has no {COMMIT_MARKER} marker — an "
            f"interrupted (uncommitted) save; it is not restorable. "
            f"restore_train_state(root) picks the latest COMMITTED "
            f"snapshot automatically", exc=InvalidArgumentError)
    try:
        with open(marker) as f:
            record = json.load(f)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise InvalidArgumentError(
            f"snapshot dir {dirname!r}: {COMMIT_MARKER} marker is corrupt "
            f"({e})") from e
    files = record.get("files", {})
    for name, size in files.items():
        path = os.path.join(dirname, name)
        enforce(os.path.exists(path),
                f"snapshot dir {dirname!r} is missing {name!r} recorded "
                f"in its {COMMIT_MARKER} marker",
                exc=InvalidArgumentError)
        got = os.path.getsize(path)
        enforce(got == int(size),
                f"snapshot dir {dirname!r}: {name!r} is {got} bytes but "
                f"the {COMMIT_MARKER} marker recorded {size} — truncated "
                f"or overwritten after commit",
                exc=InvalidArgumentError)
    n_manifests = len([n for n in os.listdir(dirname)
                       if n.startswith("manifest-")
                       and n.endswith(".json")])
    want = int(record.get("manifests", n_manifests))
    enforce(n_manifests == want,
            f"snapshot dir {dirname!r} holds {n_manifests} manifest(s) "
            f"but the {COMMIT_MARKER} marker recorded {want} — shard "
            f"files from another world mixed in?",
            exc=InvalidArgumentError)


def _resolve_snapshot_dir(path: str) -> str:
    """Accept either a snapshot dir or a root of snapshot-* dirs."""
    if os.path.basename(os.path.normpath(path)).startswith(SNAPSHOT_PREFIX):
        return path
    if os.path.isdir(path) and any(
            _SNAP_RE.match(n) for n in os.listdir(path)):
        latest = latest_snapshot(path)
        enforce(latest is not None,
                f"checkpoint root {path!r} holds snapshot dirs but none "
                f"is committed (no {COMMIT_MARKER} markers) — every save "
                f"was interrupted before its commit point",
                exc=NotFoundError)
        return latest
    return path


# ---------------------------------------------------------------------------
# train-state metadata
# ---------------------------------------------------------------------------

def _strategy_dict(strategy) -> Dict[str, Any]:
    if strategy is None:
        return {}
    from .strategy import ReduceStrategy
    return {
        "reduce_strategy": ReduceStrategy(strategy.reduce_strategy).name,
        "quant_comm": strategy.quant_comm,
        "quant_comm_block": strategy.quant_comm_block,
        "comm_error_feedback": strategy.comm_error_feedback,
        "comm_bucket_bytes": strategy.comm_bucket_bytes,
        "pipeline_stages": strategy.pipeline_stages,
        "num_microbatches": strategy.num_microbatches,
        "pipeline_schedule": strategy.pipeline_schedule,
    }


def _ef_layout(program) -> Optional[Dict[str, Any]]:
    """The error-feedback transfer layout of a comm-rewritten program:
    which grads ride which transfer, in which order, at which flat
    sizes — everything `_remap_error_feedback` needs to re-map residual
    state onto a DIFFERENT dp world (var names and row counts both
    change with dp)."""
    if not getattr(program, "_dp_comm_applied", False):
        return None
    block = program.global_block()
    comm = next((op for op in block.ops if op.type == "dp_grad_comm"), None)
    if comm is None or not comm.attrs.get("error_feedback"):
        return None
    err_names = list(comm.inputs.get("ErrIn", []))
    if not err_names:
        return None
    kinds = comm.attrs["kinds"]
    numels = comm.attrs["numels"]
    grads = list(comm.inputs["X"])
    dp = int(comm.attrs["dp"])
    tp = int(getattr(program, "_tp_size", 0) or 0) \
        if getattr(program, "_tp_applied", False) else 0
    transfers = []
    # the pass lays err state out sharded-transfers-first, then buckets —
    # mirror that order (grad_comm.py _comm_optimize_pass_impl)
    for i, kind in enumerate(kinds):
        if kind == "sharded":
            transfers.append({"kind": "sharded", "grads": [grads[i]],
                              "numels": [numels[i]], "flat": numels[i]})
    for idxs in comm.attrs["buckets"]:
        flat = sum(numels[i] for i in idxs)
        transfers.append({"kind": "bucket",
                          "grads": [grads[i] for i in idxs],
                          "numels": [numels[i] for i in idxs],
                          "flat": -(-flat // dp) * dp})
    enforce(len(transfers) == len(err_names),
            f"error-feedback layout mismatch: {len(transfers)} transfers "
            f"vs {len(err_names)} state vars", exc=InvalidArgumentError)
    for t, name in zip(transfers, err_names):
        t["var"] = name
    return {"dp": dp, "tp": max(tp, 1),
            "quant": comm.attrs["quant"], "block": comm.attrs["block"],
            "transfers": transfers}


# ---------------------------------------------------------------------------
# save
# ---------------------------------------------------------------------------

_PENDING: List["AsyncSnapshot"] = []
_pending_lock = threading.Lock()
_serial_lock = threading.Lock()
_last_serial = -1


def _alloc_serial(root: str) -> int:
    """Monotone snapshot serial: max(disk, in-process counter) under a
    lock, so two async saves racing before either's directory exists
    cannot mint the same serial (their staging dirs would collide and
    the second rename would clobber the first commit)."""
    global _last_serial
    with _serial_lock:
        snaps = list_snapshots(root, committed_only=False)
        serial = max(_last_serial + 1,
                     (snaps[-1][0] + 1) if snaps else 0)
        _last_serial = serial
        return serial


class AsyncSnapshot:
    """Handle for a background snapshot write. The device→host copy
    already happened when this handle exists — the training loop may
    mutate state freely. result() blocks until the commit (re-raising
    any writer exception) and returns the committed snapshot path."""

    def __init__(self, serial: Optional[int] = None):
        self._event = threading.Event()
        self._path: Optional[str] = None
        self._exc: Optional[BaseException] = None
        self._serial = serial

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> str:
        if not self._event.wait(timeout):
            raise TimeoutError("snapshot write not committed in time")
        if self._exc is not None:
            raise self._exc
        return self._path

    def _finish(self, path=None, exc=None):
        self._path = path
        self._exc = exc
        with _pending_lock:
            if self in _PENDING:
                _PENDING.remove(self)
        self._event.set()


def wait_for_pending(timeout: Optional[float] = None):
    """Block until every in-flight async snapshot committed — the drain
    hook (EngineServer SIGTERM drain, supervisor shutdown, end of
    training) that guarantees no writer thread is still holding dirty
    state when the process exits."""
    with _pending_lock:
        pending = list(_PENDING)
    for h in pending:
        h.result(timeout)


def _collect_train_arrays(program, scope) -> Dict[str, object]:
    from ..io import _is_persistable, _select_vars
    arrays = {}
    for v in _select_vars(program, _is_persistable):
        if scope.has_var(v.name):
            arrays[v.name] = scope.get(v.name)
    enforce(arrays, "no persistable state in scope — run the startup "
            "program before snapshotting", exc=InvalidArgumentError)
    return arrays


def _prepared_view(executor, program, scope):
    """The program AS THE EXECUTOR RUNS IT: ParallelExecutor rewrites
    (tp/dp-comm/pipeline) before compiling, and checkpoint contents +
    placement policy must follow the REWRITTEN view (sharded
    accumulators, error-feedback vars)."""
    if executor is not None and hasattr(executor, "prepare_program"):
        return executor.prepare_program(program, scope)
    return program


def save_train_state(root: str,
                     program=None, scope=None, executor=None,
                     step: int = 0, extra_meta: Optional[dict] = None,
                     max_snapshots: int = 3,
                     block: bool = True):
    """Snapshot the complete training state under `root` with the atomic
    two-phase commit. Returns the committed snapshot path (block=True)
    or an AsyncSnapshot handle (block=False: only the device→host copy
    happens on the caller's thread; a background writer does the file
    writes + commit off the step critical path).

    `executor` is the executor DRIVING training (Executor or
    ParallelExecutor): its run counter — the RNG seed stream position —
    rides the metadata, so a restored run draws exactly the seeds the
    uninterrupted run would have. ParallelExecutor additionally
    contributes its BuildStrategy/mesh config and the rewritten program
    view (sharded accumulators, error-feedback state)."""
    import jax

    from ..framework.program import default_main_program
    from ..framework.scope import global_scope
    from ..observability import tracing as _tracing
    from ..sharded_checkpoint import collect_chunks

    # single-writer protocol: the rmtree-leftovers + rename + retention
    # steps assume ONE process owns the snapshot root. In a multi-process
    # world each process would clobber its siblings' shard files (silent
    # checkpoint loss) — reject up front; the chief-commits barrier
    # protocol (trainer.save_checkpoint's multi-phase form) is the
    # planned extension (ROUND14_NOTES.md).
    enforce(jax.process_count() == 1,
            f"elastic save_train_state is single-process today "
            f"(process_count={jax.process_count()}): concurrent writers "
            f"would overwrite each other's snapshot serials. Use "
            f"trainer.save_checkpoint(sharded=True) — its barrier "
            f"protocol commits multi-host checkpoints safely",
            exc=InvalidArgumentError)
    program = program or default_main_program()
    scope = scope or global_scope()
    prepared = _prepared_view(executor, program, scope)
    arrays = _collect_train_arrays(prepared, scope)

    mesh = getattr(executor, "mesh", None)
    strategy = getattr(executor, "build_strategy", None)
    meta = {
        "format": META_FORMAT,
        "step": int(step),
        "run_counter": int(getattr(executor, "_run_counter", 0) or 0),
        "random_seed": int(program.random_seed),
        "world": dict(getattr(mesh, "axes", {}) or {}),
        "strategy": _strategy_dict(strategy),
        "ef_layout": _ef_layout(prepared),
        "extra": dict(extra_meta or {}),
        "var_names": sorted(arrays),
    }

    with _tracing.span("checkpoint", "elastic/snapshot_d2h",
                       n_vars=len(arrays), step=int(step)):
        chunks, manifest, pid = collect_chunks(arrays)

    os.makedirs(root, exist_ok=True)
    serial = _alloc_serial(root)
    final = os.path.join(root, f"{SNAPSHOT_PREFIX}{serial:08d}")
    staging = os.path.join(root,
                           f"{STAGING_PREFIX}{serial:08d}-{os.getpid()}")

    if block:
        return _write_and_commit(staging, final, chunks, manifest, pid,
                                 meta, root, max_snapshots, step,
                                 serial)
    handle = AsyncSnapshot(serial)
    with _pending_lock:
        _PENDING.append(handle)

    def _writer():
        try:
            path = _write_and_commit(staging, final, chunks, manifest,
                                     pid, meta, root, max_snapshots,
                                     step, serial)
            handle._finish(path=path)
        except BaseException as e:  # noqa: BLE001 - surfaced via result()
            handle._finish(exc=e)

    t = threading.Thread(target=_writer, name=f"ckpt-writer-{serial}",
                         daemon=True)
    t.start()
    return handle


def _write_and_commit(staging, final, chunks, manifest, pid, meta,
                      root, max_snapshots, step, serial) -> str:
    """Phase 2: staged writes, fsync, rename, COMMIT marker, retention.
    The fault-injection crash points live here (see
    fault_injection_config)."""
    from ..observability import tracing as _tracing
    from ..sharded_checkpoint import _fsync_file, write_chunks

    fault = fault_injection_config()
    slow = fault.get("slow_writer")
    if slow:
        time.sleep(float(slow))
    t0 = time.perf_counter()
    with _tracing.span("checkpoint", "elastic/snapshot_write",
                       step=int(step)):
        if os.path.isdir(staging):
            shutil.rmtree(staging)
        os.makedirs(staging)
        write_chunks(staging, chunks, manifest, pid, fsync=True)
        meta_path = os.path.join(staging, META_FILE)
        with open(meta_path, "w") as f:
            json.dump(meta, f, indent=1)
            f.flush()
            os.fsync(f.fileno())

        mid = fault.get("crash_mid_save")
        if mid is not None:
            _crash_mid_staging(staging, int(mid))  # may not return
        payload = {n: os.path.getsize(os.path.join(staging, n))
                   for n in _payload_files(staging)}
        n_manifests = len([n for n in payload if n.startswith("manifest-")])

    with _tracing.span("checkpoint", "elastic/commit", step=int(step)):
        if os.path.isdir(final):
            # leftovers of a preempted save that never committed (a
            # COMMITTED dir at this serial is impossible: the serial scan
            # above counted it)
            shutil.rmtree(final)
        os.replace(staging, final)
        _fsync_file(root)
        if mid is not None and int(mid) == sum(payload.values()):
            # crash point "after rename, before COMMIT": the snapshot dir
            # is visible but uncommitted — restore must skip it
            _sigkill_self()  # pragma: no cover
        marker = os.path.join(final, COMMIT_MARKER)
        with open(marker + ".tmp", "w") as f:
            json.dump({"manifests": n_manifests, "files": payload,
                       "step": int(step)}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(marker + ".tmp", marker)
        _fsync_file(final)
    if mid is not None and int(mid) > sum(payload.values()):
        _sigkill_self()  # pragma: no cover

    # retention: keep the newest max_snapshots COMMITTED snapshots; also
    # sweep stale staging dirs from earlier preempted/dead saves — but
    # never one a LIVE async writer of this process still owns (its
    # serial is >= the oldest pending serial)
    if max_snapshots and max_snapshots > 0:
        committed = list_snapshots(root, committed_only=True)
        for _, old in committed[:-max_snapshots]:
            shutil.rmtree(old, ignore_errors=True)
    with _pending_lock:
        live = {h._serial for h in _PENDING if h._serial is not None}
    floor = min(live | {serial})
    stale_re = re.compile(re.escape(STAGING_PREFIX) + r"(\d+)-")
    for name in os.listdir(root):
        m = stale_re.match(name)
        if m and int(m.group(1)) < floor and \
                os.path.join(root, name) != staging:
            shutil.rmtree(os.path.join(root, name), ignore_errors=True)

    dt = time.perf_counter() - t0
    _metric("ptpu_ckpt_saves_total").inc()
    _metric("ptpu_ckpt_save_bytes_total").inc(sum(payload.values()))
    _metric("ptpu_ckpt_save_seconds").observe(dt)
    flags.vlog(1, "committed snapshot %s (%d bytes, %.3fs)", final,
               sum(payload.values()), dt)
    return final


# ---------------------------------------------------------------------------
# error-feedback N→M re-mapping
# ---------------------------------------------------------------------------

def _resize_replica_rows(rows: np.ndarray, new_n: int) -> np.ndarray:
    """Re-map per-replica residual rows [N, n] onto M replicas while
    preserving the EFFECTIVE pending gradient: each step applies
    mean_i(g_i + e_i), so the pending correction is (1/N)·Σe — rows are
    scaled by M/N so (1/M)·Σe' == (1/N)·Σe exactly. Growing pads zero
    rows (new replicas start with no residual); shrinking folds rows
    modulo M. pad-then-fold is the identity, so an N→M→N round trip with
    M ≥ N restores the original rows bit-exactly when M/N is a power of
    two (f32 scaling by powers of two is exact)."""
    n_old = rows.shape[0]
    scale = np.float32(new_n) / np.float32(n_old)
    out = np.zeros((new_n,) + rows.shape[1:], rows.dtype)
    if new_n >= n_old:
        out[:n_old] = rows
    else:
        for i in range(n_old):
            out[i % new_n] += rows[i]
    return (out * scale).astype(rows.dtype)


def _remap_error_feedback(ckpt, old_layout: Dict, new_layout: Dict,
                          new_dp: int) -> Dict[str, np.ndarray]:
    """Saved residual state (old transfer layout, N rows) → host arrays
    for the NEW layout's error-feedback vars (M rows). Per-gradient
    segments are extracted from the old flat vectors, dp rows re-mapped
    within each tp group, and re-packed at the new offsets — gradients
    may move between transfers when the dp divisibility classification
    changes with the resize. Bucket pad regions carry an identically
    zero residual (quantizing an exact zero leaves no residual), so
    dropping/re-padding them is lossless."""
    enforce(old_layout["tp"] == new_layout["tp"],
            f"elastic restore resizes the dp axis only: checkpoint has "
            f"tp={old_layout['tp']}, target program tp={new_layout['tp']}",
            exc=InvalidArgumentError)
    enforce((old_layout["quant"], old_layout["block"])
            == (new_layout["quant"], new_layout["block"]),
            f"error-feedback state is only meaningful under the wire "
            f"config that produced it: checkpoint quant="
            f"{old_layout['quant']!r}/block={old_layout['block']} vs "
            f"target {new_layout['quant']!r}/{new_layout['block']} — "
            f"restore with the same quant_comm config, or drop "
            f"comm_error_feedback to start residuals at zero",
            exc=InvalidArgumentError)
    tp = old_layout["tp"]
    old_dp = int(old_layout["dp"])

    # old per-grad residual matrices: grad -> [tp, N, numel]
    per_grad: Dict[str, np.ndarray] = {}
    for t in old_layout["transfers"]:
        arr = np.asarray(ckpt.read(t["var"]))
        enforce(arr.shape == (old_dp * tp, t["flat"]),
                f"saved error-feedback var {t['var']!r} has shape "
                f"{arr.shape}, expected {(old_dp * tp, t['flat'])} — "
                f"checkpoint metadata disagrees with its contents",
                exc=InvalidArgumentError)
        arr = arr.reshape(tp, old_dp, t["flat"])
        off = 0
        for g, n in zip(t["grads"], t["numels"]):
            per_grad[g] = arr[:, :, off:off + n]
            off += n

    out: Dict[str, np.ndarray] = {}
    for t in new_layout["transfers"]:
        new = np.zeros((tp, new_dp, t["flat"]), np.float32)
        off = 0
        for g, n in zip(t["grads"], t["numels"]):
            old = per_grad.get(g)
            if old is not None:
                enforce(old.shape[-1] == n,
                        f"gradient {g!r} changed size across the resize "
                        f"({old.shape[-1]} vs {n}) — the checkpoint does "
                        f"not match this program",
                        exc=InvalidArgumentError)
                for ti in range(tp):
                    new[ti, :, off:off + n] = _resize_replica_rows(
                        old[ti], new_dp)
            off += n
        out[t["var"]] = new.reshape(tp * new_dp, t["flat"])
    return out


# ---------------------------------------------------------------------------
# restore
# ---------------------------------------------------------------------------

def read_meta(dirname: str) -> Dict[str, Any]:
    """The train_meta.json of a snapshot dir (resolves a root to its
    latest committed snapshot first)."""
    dirname = _resolve_snapshot_dir(dirname)
    validate_snapshot(dirname)
    with open(os.path.join(dirname, META_FILE)) as f:
        return json.load(f)


def verify_restored_placement(executor, program, scope,
                              names=None) -> List[str]:
    """Static placement check of live state vs the executor's policy:
    for every persistable in `names` (default: all in scope), the
    array's sharding must be equivalent to what
    ParallelExecutor._state_sharding demands for this program. Returns a
    list of violation strings (empty = clean) — restore_train_state
    enforces on them; tools/lint_program.py --restore_dir reports them."""
    from ..io import _is_persistable, _select_vars
    problems = []
    if not hasattr(executor, "state_sharding"):
        return problems
    for v in _select_vars(program, _is_persistable):
        if names is not None and v.name not in names:
            continue
        if not scope.has_var(v.name):
            continue
        val = scope.get(v.name)
        sh = getattr(val, "sharding", None)
        if sh is None:
            continue
        want = executor.state_sharding(program, v.name)
        if not sh.is_equivalent_to(want, getattr(val, "ndim", 0)):
            problems.append(
                f"{v.name}: restored with {sh.spec}, executor places it "
                f"{want.spec}")
    return problems


def restore_train_state(path: str,
                        program=None, scope=None, executor=None,
                        strict: bool = True,
                        verify: bool = True) -> Dict[str, Any]:
    """Restore the latest committed snapshot under `path` (or `path`
    itself when it is a snapshot dir) into `scope`, re-placing every
    array onto the CURRENT executor's mesh — which may have a different
    dp degree than the one that saved (elastic N→M resize): parameters
    and full-shape ZeRO-1 accumulator chunks re-shard through
    make_array_from_callback; error-feedback residuals re-map through
    `_remap_error_feedback`. Restores the executor's run counter (the
    RNG seed stream position), so a fixed-seed resumed run replays
    exactly the seeds of the uninterrupted one.

    verify=True (default) runs the r10/r13 static analyzer
    (`verify_program`) over the program as the executor rewrites it and
    checks every restored array's placement against the executor's
    policy BEFORE returning — a mis-placed restore fails here, not in
    jit's arg-sharding check mid-step.

    strict=True errors on persistables the checkpoint lacks; False
    leaves them at their startup values (warm-starting a grown model).

    Returns the snapshot metadata (step, extra, world, strategy...)."""
    import time as _time

    from ..framework.program import default_main_program
    from ..framework.scope import global_scope
    from ..io import _is_persistable, _select_vars
    from ..observability import tracing as _tracing
    from ..sharded_checkpoint import ShardedCheckpoint, restore_array

    t0 = _time.perf_counter()
    program = program or default_main_program()
    scope = scope or global_scope()
    dirname = _resolve_snapshot_dir(path)
    validate_snapshot(dirname)
    with open(os.path.join(dirname, META_FILE)) as f:
        meta = json.load(f)

    prepared = _prepared_view(executor, program, scope)
    new_ef = _ef_layout(prepared)
    old_ef = meta.get("ef_layout")
    mesh = getattr(executor, "mesh", None)
    new_dp = int(mesh.axis_size("dp")) if mesh is not None else 1

    with _tracing.span("checkpoint", "elastic/restore",
                       snapshot=os.path.basename(dirname)):
        ckpt = ShardedCheckpoint(dirname)
        saved = set(ckpt.names())
        ef_vars = {t["var"] for t in (new_ef or {}).get("transfers", ())}
        restorable, missing = [], []
        for v in _select_vars(prepared, _is_persistable):
            name = v.name
            if name in ef_vars:
                continue  # handled below via the layout re-map
            if name not in saved:
                if getattr(v, "dp_replica_state", False):
                    continue  # stale EF var of another config: skip
                missing.append(name)
                continue
            restorable.append(name)
        # the strict check fires BEFORE any scope mutation: a caller that
        # catches it and falls back must not be left with exactly the
        # half-restored mixed state the error exists to prevent
        enforce(not (strict and missing),
                f"snapshot {dirname!r} lacks persistable var(s) "
                f"{missing[:8]}{'...' if len(missing) > 8 else ''} that "
                f"this program declares — restoring it would silently "
                f"mix checkpointed and freshly initialized state. Pass "
                f"strict=False to warm-start the missing vars from their "
                f"startup values", exc=InvalidArgumentError)
        for name in restorable:
            sharding = (executor.state_sharding(prepared, name)
                        if hasattr(executor, "state_sharding") else None)
            scope.set_var(name, restore_array(ckpt, name, sharding))

        if new_ef is not None:
            enforce(old_ef is not None,
                    f"this program carries error-feedback state "
                    f"(comm_error_feedback) but snapshot {dirname!r} "
                    f"recorded none — it was saved without quantized "
                    f"error feedback. Restore with the saving config, or "
                    f"disable comm_error_feedback to start residuals at "
                    f"zero", exc=InvalidArgumentError)
            import jax
            remapped = _remap_error_feedback(ckpt, old_ef, new_ef, new_dp)
            for name, host in remapped.items():
                sharding = (executor.state_sharding(prepared, name)
                            if hasattr(executor, "state_sharding")
                            else None)
                val = (jax.device_put(host, sharding)
                       if sharding is not None else host)
                scope.set_var(name, val)

    if executor is not None and "run_counter" in meta:
        executor._run_counter = int(meta["run_counter"])
    if strict and "random_seed" in meta:
        enforce(int(program.random_seed) == int(meta["random_seed"]),
                f"program.random_seed={program.random_seed} but the "
                f"snapshot was trained with random_seed="
                f"{meta['random_seed']}: the resumed seed stream would "
                f"diverge from the uninterrupted run. Rebuild the "
                f"program with the saved seed (or strict=False to accept "
                f"the divergence)", exc=InvalidArgumentError)

    if verify:
        from ..framework.analysis import verify_program
        errors = [d for d in verify_program(prepared)
                  if d.severity == "error"]
        enforce(not errors,
                "restored program failed static verification:\n  "
                + "\n  ".join(str(d) for d in errors[:10]),
                exc=InvalidArgumentError)
        problems = verify_restored_placement(executor, prepared, scope)
        enforce(not problems,
                "restored state placement disagrees with the executor's "
                "policy:\n  " + "\n  ".join(problems[:10]),
                exc=InvalidArgumentError)

    _metric("ptpu_ckpt_restores_total").inc()
    _metric("ptpu_ckpt_restore_seconds").observe(_time.perf_counter() - t0)
    return meta
