"""Mesh-to-mesh resharding planner for elastic restore.

`restore_train_state` re-lays checkpointed state across an ARBITRARY
mesh change (dp2×tp2 → dp4, dp2×pp2 → dp2×tp2, ...). Mechanically the
checkpoint path does this through `jax.make_array_from_callback`: each
new device reads only the byte ranges of the old shard coverage its new
slice intersects. This module makes that re-layout a first-class,
inspectable PLAN — the checkpoint-mediated form of "Memory-efficient
array redistribution through portable collective communication"
(PAPERS.md): plan the transition as a collective sequence instead of
round-tripping full arrays through host memory.

Per variable the planner derives:

- the OLD shard coverage from the snapshot's chunk grid (distinct chunk
  starts per dim — no metadata needed beyond the manifests themselves;
  `train_meta.json` `placements` adds the axis NAMES for display);
- the NEW placement from the target executor's policy
  (`ParallelExecutor.state_sharding`);
- a **read plan**: exactly which chunks each new device must load
  (what `sharded_checkpoint.read_slice` will actually touch) with the
  intersection byte counts — "reads only the byte ranges each new rank
  needs" is checkable, not asserted;
- the **equivalent on-hardware redistribution schedule**: the canonical
  collective sequence that would perform the same re-layout without a
  host round trip, in the redistribution algebra

      refine     old factor divides the new one: dynamic-slice, 0 wire
      all-gather an incompatible dim un-shards over its old group
                 (ring accounting, framework/costs.py), then slices

  validated structurally against `framework.costs.reshard_wire_bytes` —
  the closed-form prediction and the step-priced schedule must agree
  EXACTLY (the r08/r11 census discipline, applied to restore).

Error-feedback residuals are NOT part of the per-variable schedule:
their resize is a semantic re-pack through the gradient space
(`elastic._remap_error_feedback`), host-mediated by design; the plan
lists them separately with their byte sizes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.enforce import InvalidArgumentError, enforce
from ..framework import costs as _costs


@dataclass
class ReshardStep:
    """One schedule entry for one variable."""
    var: str
    kind: str            # "all-gather" | "refine-slice" | "identity"
    dim: int             # tensor dim the step acts on (-1 for identity)
    group: int           # collective group size (1 for local steps)
    out_bytes: int       # per-device OUTPUT bytes of the collective
    wire_bytes: float    # per-device interconnect bytes (ring model)
    axes: Tuple[str, ...] = ()   # mesh axis names involved, for display

    def __str__(self):
        ax = "/".join(self.axes) or "-"
        return (f"{self.var}: {self.kind} dim={self.dim} group="
                f"{self.group} axes={ax} out={self.out_bytes}B "
                f"wire={self.wire_bytes:.0f}B")


@dataclass
class VariablePlan:
    var: str
    shape: Tuple[int, ...]
    nbytes: int
    old_factors: Tuple[int, ...]
    new_factors: Tuple[int, ...]
    steps: List[ReshardStep] = field(default_factory=list)
    #: chunk keys ((file, key, intersect_bytes)) the new placement reads
    reads: List[Tuple[str, str, int]] = field(default_factory=list)

    @property
    def wire_bytes(self) -> float:
        return sum(s.wire_bytes for s in self.steps)

    @property
    def read_bytes(self) -> int:
        return sum(b for _, _, b in self.reads)


@dataclass
class ReshardPlan:
    old_world: Dict[str, int]
    new_world: Dict[str, int]
    variables: Dict[str, VariablePlan] = field(default_factory=dict)
    ef_vars: Dict[str, int] = field(default_factory=dict)  # name -> bytes

    @property
    def wire_bytes(self) -> float:
        return sum(v.wire_bytes for v in self.variables.values())

    @property
    def read_bytes(self) -> int:
        return sum(v.read_bytes for v in self.variables.values())

    def moved_vars(self) -> List[str]:
        """Variables whose re-layout puts bytes on the wire."""
        return sorted(n for n, v in self.variables.items()
                      if v.wire_bytes > 0)

    def summary(self) -> Dict[str, Any]:
        kinds: Dict[str, int] = {}
        for v in self.variables.values():
            for s in v.steps:
                kinds[s.kind] = kinds.get(s.kind, 0) + 1
        return {
            "old_world": dict(self.old_world),
            "new_world": dict(self.new_world),
            "n_vars": len(self.variables),
            "n_moved": len(self.moved_vars()),
            "wire_bytes": self.wire_bytes,
            "read_bytes": self.read_bytes,
            "steps": kinds,
            "ef_vars": dict(self.ef_vars),
        }


def _coverage_factors(entry: Dict, shape: Sequence[int]) -> Tuple[int, ...]:
    """Old shard factors per dim from the chunk grid: the number of
    distinct chunk start offsets along each dim. A replicated save has
    one chunk covering the whole array (all factors 1); a dp4-sharded
    dim 0 has 4 distinct starts."""
    rank = len(shape)
    if rank == 0:
        return ()
    starts = [set() for _ in range(rank)]
    for c in entry["chunks"]:
        cs = c["start"] or [0] * rank
        for d in range(rank):
            starts[d].add(int(cs[d]))
    return tuple(max(1, len(s)) for s in starts)


def _spec_factors(spec, mesh_axes: Dict[str, int],
                  rank: int) -> Tuple[Tuple[int, ...],
                                      Tuple[Tuple[str, ...], ...]]:
    """New shard factors (and the axis names behind them) per dim from a
    PartitionSpec-style entry list."""
    factors, names = [], []
    entries = list(spec or ())
    entries += [None] * (rank - len(entries))
    for s in entries[:rank]:
        if s is None:
            factors.append(1)
            names.append(())
            continue
        axes = tuple(s) if isinstance(s, (tuple, list)) else (s,)
        f = 1
        for a in axes:
            f *= int(mesh_axes.get(a, 1))
        factors.append(f)
        names.append(axes)
    return tuple(factors), tuple(names)


def schedule_steps(var: str, shape: Sequence[int], itemsize: int,
                   old_factors: Sequence[int],
                   new_factors: Sequence[int],
                   old_axes: Sequence[Tuple[str, ...]] = (),
                   new_axes: Sequence[Tuple[str, ...]] = ()
                   ) -> List[ReshardStep]:
    """The canonical redistribution schedule for one variable, in the
    same algebra `costs.reshard_wire_bytes` prices:

    phase 1 — every dim whose new factor is a multiple of its current
    one refines by dynamic-slice (0 wire); phase 2 — each remaining
    incompatible dim all-gathers over its old group (output bytes
    computed at the CURRENT factors of the other dims — refinement
    first makes the gathers cheaper, the memory-efficient ordering),
    then slices to the new factor."""
    shape = tuple(int(d) for d in shape)
    nbytes = int(np.prod(shape, dtype=np.int64)) * itemsize \
        if shape else itemsize
    rank = len(shape)
    cur = list(old_factors) + [1] * (rank - len(old_factors))
    new = list(new_factors) + [1] * (rank - len(new_factors))
    for d in range(rank):
        enforce(shape[d] % max(cur[d], 1) == 0
                and shape[d] % max(new[d], 1) == 0,
                f"{var!r} dim {d} ({shape[d]}) does not divide by its "
                f"shard factors (old {cur[d]}, new {new[d]})",
                exc=InvalidArgumentError)
    steps: List[ReshardStep] = []

    def _ax(axes_list, d):
        return tuple(axes_list[d]) if d < len(axes_list) else ()

    # phase 1: refinement slices (and identity detection)
    for d in range(rank):
        if new[d] == cur[d]:
            continue
        if new[d] % cur[d] == 0:
            cur[d] = new[d]
            steps.append(ReshardStep(var, "refine-slice", d, 1, 0, 0.0,
                                     _ax(new_axes, d)))
    # phase 2: incompatible dims gather over the old group, then slice
    for d in range(rank):
        if cur[d] == new[d]:
            continue
        others = 1
        for d2 in range(rank):
            if d2 != d:
                others *= cur[d2]
        out = nbytes // others
        g = cur[d]
        wire = _costs.collective_wire_bytes("all-gather", out, g)
        steps.append(ReshardStep(var, "all-gather", d, g, out, wire,
                                 _ax(old_axes, d)))
        cur[d] = 1
        if new[d] > 1:
            steps.append(ReshardStep(var, "refine-slice", d, 1, 0, 0.0,
                                     _ax(new_axes, d)))
            cur[d] = new[d]
    if not steps:
        steps.append(ReshardStep(var, "identity", -1, 1, 0, 0.0))
    return steps


def _chunk_reads(entry: Dict, shape: Sequence[int],
                 itemsize: int, sharding) -> List[Tuple[str, str, int]]:
    """Which chunks (and how many intersecting bytes) the NEW placement
    reads: the union over the new sharding's distinct device slices of
    the chunks they intersect — exactly what read_slice will touch."""
    rank = len(shape)
    if rank == 0 or sharding is None:
        return [(c["file"], c["key"],
                 int(np.prod(c["shape"], dtype=np.int64)) * itemsize
                 if c["shape"] else itemsize)
                for c in entry["chunks"]]
    # distinct slices across devices (replicated devices share one)
    slices = set()
    for idx in sharding.devices_indices_map(tuple(shape)).values():
        norm = tuple((sl.indices(dim)[0], sl.indices(dim)[1])
                     for sl, dim in zip(idx, shape))
        slices.add(norm)
    reads: Dict[Tuple[str, str], int] = {}
    for c in entry["chunks"]:
        cs = c["start"] or [0] * rank
        ce = [s + d for s, d in zip(cs, c["shape"])]
        for sl in slices:
            inter = 1
            for (a, b), s, e in zip(sl, cs, ce):
                lo, hi = max(a, s), min(b, e)
                if lo >= hi:
                    inter = 0
                    break
                inter *= hi - lo
            if inter:
                key = (c["file"], c["key"])
                reads[key] = reads.get(key, 0) + inter * itemsize
    return [(f, k, b) for (f, k), b in sorted(reads.items())]


def plan_restore(ckpt, meta: Dict, prepared, executor,
                 names: Optional[Sequence[str]] = None) -> ReshardPlan:
    """Build the full mesh-resize plan for restoring checkpoint `ckpt`
    (a sharded_checkpoint.ShardedCheckpoint) with metadata `meta` onto
    `executor` running `prepared` (the REWRITTEN program view). `names`
    defaults to every saved variable the program declares."""
    from ..io import _is_persistable, _select_vars

    mesh = getattr(executor, "mesh", None)
    new_world = dict(getattr(mesh, "axes", {}) or {})
    plan = ReshardPlan(old_world=dict(meta.get("world", {}) or {}),
                       new_world=new_world)
    placements = meta.get("placements") or {}
    ef_vars = {t["var"] for t in (meta.get("ef_layout") or {})
               .get("transfers", ())}
    saved = ckpt.vars
    declared = {v.name for v in _select_vars(prepared, _is_persistable)}
    for name in (names if names is not None else sorted(saved)):
        entry = saved.get(name)
        if entry is None:
            continue
        if name in ef_vars:
            shape = entry["shape"]
            nbytes = int(np.prod(shape, dtype=np.int64)) * 4 \
                if shape else 4
            plan.ef_vars[name] = nbytes
            continue
        if names is None and name not in declared:
            continue  # stale state of another config (old EF vars etc.)
        shape = tuple(int(d) for d in entry["shape"])
        itemsize = np.dtype(entry["dtype"]).itemsize \
            if entry["dtype"] != "bfloat16" else 2
        old_factors = _coverage_factors(entry, shape)
        old_spec = placements.get(name)
        old_axes = tuple(tuple(s) if s else () for s in (old_spec or ()))
        sharding = (executor.state_sharding(prepared, name)
                    if hasattr(executor, "state_sharding") else None)
        spec = tuple(getattr(sharding, "spec", ()) or ())
        new_factors, new_axes = _spec_factors(spec, new_world, len(shape))
        nbytes = int(np.prod(shape, dtype=np.int64)) * itemsize \
            if shape else itemsize
        vp = VariablePlan(name, shape, nbytes, old_factors, new_factors)
        vp.steps = schedule_steps(name, shape, itemsize, old_factors,
                                  new_factors, old_axes, new_axes)
        vp.reads = _chunk_reads(entry, shape, itemsize, sharding)
        plan.variables[name] = vp
    return plan


def validate_schedule(plan: ReshardPlan) -> List[str]:
    """Cross-check every variable's step-priced schedule against the
    closed-form `costs.reshard_wire_bytes` prediction. Returns a list of
    mismatch strings (empty = the schedule balances exactly)."""
    problems = []
    for name, vp in plan.variables.items():
        want = _costs.reshard_wire_bytes(vp.nbytes, vp.old_factors,
                                         vp.new_factors)
        got = vp.wire_bytes
        if got != want:
            problems.append(f"{name}: schedule prices {got} wire bytes, "
                            f"costs.reshard_wire_bytes predicts {want}")
    return problems
