"""ParallelExecutor: SPMD data-parallel program execution.

≙ reference framework/parallel_executor.cc:119 + python/paddle/fluid/
parallel_executor.py:32. The reference replicates block-0 onto every GPU,
inserts NCCL all-reduce op handles per gradient, and schedules the SSA graph
with a thread pool. The TPU-native design compiles the SAME single-device
program once under `jax.jit` with sharding annotations:

- feed tensors are sharded along dim 0 over the mesh's data axis
  (≙ FeedAndSplitTensorIntoLocalScopes / SplitLoDTensor,
  parallel_executor.cc:333);
- parameters are replicated (≙ BCastParamsToDevices, :210);
- XLA's SPMD partitioner then emits the per-gradient all-reduce on ICI that
  the reference builds explicitly (multi_devices_graph_pass.cc:419-425);
- with `ReduceStrategy.Reduce`, optimizer accumulators are sharded across
  the data axis instead — XLA lowers the update to reduce-scatter + sharded
  optimizer math + all-gather, the ZeRO-1 formulation of the reference's
  reduce-to-one-owner-then-broadcast mode (:412-418,445-453).

Because the mean loss is computed over the *global* (sharded) batch, loss
scaling by 1/num_devices (≙ ScaleLossGradOpHandle) is implicit.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..core.enforce import InvalidArgumentError, enforce
from ..framework.executor import Executor
from ..framework.program import Program, Variable, default_main_program
from ..framework.scope import Scope, global_scope
from . import grad_comm as _grad_comm
from . import pipeline as _pipeline
from . import tensor_parallel as _tensor_parallel
from .mesh import (DATA_AXIS, MODEL_AXIS, PIPELINE_AXIS, SEQUENCE_AXIS,
                   DeviceMesh, get_default_mesh, shard_map as _shard_map)
from .strategy import (BuildStrategy, ExecutionStrategy,
                       GradientScaleStrategy, ReduceStrategy)


class ParallelExecutor(Executor):
    """Drop-in multi-device executor (≙ fluid.ParallelExecutor)."""

    def __init__(self,
                 use_tpu: bool = True,
                 loss_name: Optional[str] = None,
                 main_program: Optional[Program] = None,
                 share_vars_from: Optional["ParallelExecutor"] = None,
                 exec_strategy: Optional[ExecutionStrategy] = None,
                 build_strategy: Optional[BuildStrategy] = None,
                 num_trainers: int = 1,
                 trainer_id: int = 0,
                 scope: Optional[Scope] = None,
                 mesh: Optional[DeviceMesh] = None):
        super().__init__()
        self.mesh = mesh or get_default_mesh()
        self.loss_name = loss_name
        self.main_program = main_program
        self.exec_strategy = exec_strategy or ExecutionStrategy()
        self.build_strategy = build_strategy or BuildStrategy()
        self.scope = scope or global_scope()
        if share_vars_from is not None:
            self.scope = share_vars_from.scope
        self._dp = self.mesh.axis_size(DATA_AXIS)
        self._feed_shapes: Dict[str, tuple] = {}
        self._comm_cache: Dict[Any, Program] = {}
        self._pp_cache: Dict[Any, Program] = {}
        self._tp_cache: Dict[Any, Program] = {}
        if (_grad_comm.explicit_comm_config(self.build_strategy) is not None):
            enforce(DATA_AXIS in self.mesh.axes,
                    f"the explicit gradient pipeline (ReduceScatter / "
                    f"quant_comm) needs a {DATA_AXIS!r} axis in the mesh, "
                    f"got axes {self.mesh.axis_names}",
                    exc=InvalidArgumentError)
        if (self.build_strategy.gradient_scale_strategy
                == GradientScaleStrategy.CoeffNumDevice):
            raise NotImplementedError(
                "GradientScaleStrategy.CoeffNumDevice is not implemented: "
                "under SPMD the global-batch `mean` already scales the loss "
                "gradient; build the program with a mean-reduced loss "
                "(GradientScaleStrategy.One) instead")

    # -- sharding assignment ---------------------------------------------
    def _find_var(self, program: Program, name: str) -> Optional[Variable]:
        for b in program.blocks:
            if b.has_var(name):
                return b.var(name)
        return None

    def _state_sharding(self, program: Program, name: str) -> NamedSharding:
        v = self._find_var(program, name)
        spec = getattr(v, "sharding_spec", None) if v is not None else None
        manual = (getattr(program, "_dp_comm_applied", False)
                  or getattr(program, "_pp_applied", False))
        if spec is not None and not (
                manual and v is not None
                and (getattr(v, "dp_shard_update", False)
                     or getattr(v, "dp_replica_state", False)
                     or getattr(v, "tp_spec", None))):
            # explicit TP/EP placement from ParamAttr(sharding_spec=...) or
            # parallel.auto_shard annotation; mesh.sharding drops axis names
            # not present in this mesh (replicated there). In the MANUAL
            # modes a var the rewrite passes marked is placed by its
            # markers below instead: optimizer.py copies the param's
            # sharding_spec onto same-shaped accumulators, and an
            # annotation-only placement would drop the ZeRO dim-0/dp
            # component a dp_shard_update accumulator needs (caught by
            # the r19 planner sweep: tp-annotated transformer + Adam +
            # sharded update crashed the per-shard optimizer math on a
            # tp-only moment slice).
            return self.mesh.sharding(*spec)
        if manual:
            # manual (explicit-comm and/or pipeline) modes: placement
            # follows the rewrite passes' markers — tp_shard_pass marks
            # tensor-parallel state with `tp_spec` (lives split over tp);
            # sharded-update accumulators and per-replica error-feedback
            # state live split on dim 0 over dp, composing with tp as
            # tp-major on a shared dim (dp_shard_slice slices WITHIN the
            # tp-local block). Everything else is replicated (the Reduce
            # heuristic below must NOT apply: an accumulator left on the
            # full-update path is consumed whole per shard).
            if v is None or not v.shape:
                return self.mesh.replicated()
            rank = len(v.shape)
            tp_spec = list(getattr(v, "tp_spec", None) or ())
            tp_spec += [None] * (rank - len(tp_spec))
            entries: List[Any] = [MODEL_AXIS if s == MODEL_AXIS else None
                                  for s in tp_spec[:rank]]
            if (getattr(v, "dp_shard_update", False)
                    or getattr(v, "dp_replica_state", False)):
                entries[0] = ((MODEL_AXIS, DATA_AXIS)
                              if entries[0] == MODEL_AXIS else DATA_AXIS)
            if not any(e is not None for e in entries):
                return self.mesh.replicated()
            return self.mesh.sharding(*entries)
        if (self.build_strategy.reduce_strategy == ReduceStrategy.Reduce
                and v is not None
                and getattr(v, "is_optimizer_state", False)
                and v.shape and len(v.shape) >= 1
                and v.shape[0] >= self._dp and v.shape[0] % self._dp == 0):
            # ZeRO-1: shard the accumulator's dim 0 across the data axis.
            return self.mesh.sharding(DATA_AXIS,
                                      *([None] * (len(v.shape) - 1)))
        return self.mesh.replicated()

    def _batch_led_feed(self, program: Program, name: str) -> bool:
        """A feed DECLARED batch-led ([-1, ...]) — or undeclared (sidecars
        like @SEQLEN, batch-led by construction). Shared rule with
        _pad_for_dp."""
        v = self._find_var(program, name)
        shape = getattr(v, "shape", None) if v is not None else None
        return shape is None or (bool(shape) and shape[0] == -1)

    def _feed_sharding(self, program: Program, name: str,
                       shape) -> NamedSharding:
        if not shape:  # scalar feed
            return self.mesh.replicated()
        if ((getattr(program, "_dp_comm_applied", False)
             or getattr(program, "_pp_applied", False))
                and not self._batch_led_feed(program, name)):
            # manual modes: the per-shard step consumes a fixed-shape
            # auxiliary feed WHOLE — splitting it would hand each shard a
            # fragment (the SPMD partitioner can split it safely; manual
            # per-shard code cannot)
            return self.mesh.replicated()
        if (self.build_strategy.enable_sequence_parallel and len(shape) >= 2):
            v = self._find_var(program, name)
            if v is not None and getattr(v, "lod_level", 0) > 0:
                # sequence feed [B, T, ...]: split T over the sequence axis
                # too (context parallelism; ring attention consumes this
                # layout — parallel/ring_attention.py).
                return self.mesh.sharding(DATA_AXIS, SEQUENCE_AXIS,
                                          *([None] * (len(shape) - 2)))
        return self.mesh.sharding(DATA_AXIS, *([None] * (len(shape) - 1)))

    # -- compile with shardings ------------------------------------------
    def _step_shardings(self, program, feed_names, fetch_names, ro, rw,
                        state_out_names):
        """The ONE place per-name placement policy lives: shardings for a
        single step's (feeds, ro, rw, seed) inputs and (fetches, state)
        outputs — both the single-step compile and the scan-fused
        run_steps derive from it."""
        feed_shard = tuple(self._feed_sharding(program, n,
                                               self._feed_shapes.get(n))
                           for n in feed_names)
        ro_shard = tuple(self._state_sharding(program, n) for n in ro)
        rw_shard = tuple(self._state_sharding(program, n) for n in rw)
        repl = self.mesh.replicated()
        fetch_shard = tuple(repl for _ in fetch_names)
        state_out_shard = tuple(self._state_sharding(program, n)
                                for n in state_out_names)
        return ((feed_shard, ro_shard, rw_shard, repl),
                (fetch_shard, state_out_shard))

    def _compile(self, program: Program, scope: Scope, feed_names, fetch_names,
                 in_shardings=None, out_shardings=None, analysis=None):
        program = self._prepare_program(program, scope)
        analysis = analysis or self._analyze_state(program, scope, feed_names,
                                                   fetch_names)
        ro, rw, out_only = analysis
        state_out_names = sorted(set(rw) | set(out_only))
        in_sh, out_sh = self._step_shardings(program, feed_names,
                                             fetch_names, ro, rw,
                                             state_out_names)
        return super()._compile(
            program, scope, feed_names, fetch_names,
            in_shardings=in_sh, out_shardings=out_sh, analysis=analysis)

    # -- explicit gradient-comm pipeline (parallel/grad_comm.py) ----------
    def _gate_manual_mode(self, program: Program, what: str):
        """Gates for the full-manual execution modes (explicit dp comm,
        pipeline), naming exactly the combinations that remain
        unsupported. tp-sharded parameters are NOT gated anymore: the
        tp_shard_pass (framework/sharding.py) rewrites them into explicit
        tp collectives before this gate runs (r11). Still rejected:

          1. sequence-parallel feed splitting (enable_sequence_parallel):
             the manual step consumes whole per-shard sequences, so an
             sp-split feed — with or without TP — would hand each shard a
             sequence fragment. Use the SPMD AllReduce/Reduce strategies
             for sp programs.
          2. parameters sharded over a NON-tp mesh axis (dp/sp-sharded
             annotations): no rewrite pass owns those placements in the
             manual modes.
          3. tp-sharded parameters while the PTPU_TP_SHARD=0 kill switch
             is down (the pass that makes them executable is disabled)."""
        enforce(not self.build_strategy.enable_sequence_parallel,
                f"{what} runs the step manually over the whole mesh and "
                f"consumes each dp shard's sequences WHOLE, so "
                f"sequence-parallel feed splitting "
                f"(enable_sequence_parallel) cannot compose with it (with "
                f"or without TP). Use the SPMD AllReduce/Reduce "
                f"strategies for sp programs; tp-sharded params compose "
                f"with {what} via the tp_shard_pass path",
                exc=InvalidArgumentError)
        from ..core import flags
        from ..framework.sharding import tp_component
        for b in program.blocks:
            for v in b.vars.values():
                spec = getattr(v, "sharding_spec", None)
                # only a spec that still names a LIVE axis on this mesh is
                # truly sharded — an annotation resolving to all-None
                # (general-mesh annotation run on a dp-only mesh) is
                # replicated and composes fine
                if not v.persistable or spec is None:
                    continue
                axes = set()
                for s in self.mesh.pspec(*spec):
                    if isinstance(s, (tuple, list)):
                        axes.update(s)
                    elif s is not None:
                        axes.add(s)
                non_tp = sorted(axes - {MODEL_AXIS})
                if non_tp:
                    raise InvalidArgumentError(
                        f"parameter {v.name!r} is sharded over mesh "
                        f"axes {non_tp} — {what} runs the step manually "
                        f"and only the tp axis has a rewrite pass "
                        f"(tp_shard_pass) that splices the needed "
                        f"collectives. Shard parameters over {MODEL_AXIS!r} "
                        f"only, or use the SPMD AllReduce/Reduce "
                        f"strategies for {non_tp}-sharded placements")
                if axes and not getattr(program, "_tp_applied", False):
                    if not flags.get_flag("tp_shard"):
                        hint = ("the PTPU_TP_SHARD=0 kill switch disabled "
                                "the tp_shard_pass rewrite; flip it back "
                                "to 1")
                    elif v.name not in program.global_block().vars:
                        hint = ("the annotation sits on a SUB-BLOCK "
                                "parameter; the sharding subsystem "
                                "propagates over the global block only — "
                                "hoist the parameter to block 0 or drop "
                                "its annotation")
                    else:
                        hint = "tp_shard_pass did not run — executor bug"
                    raise InvalidArgumentError(
                        f"parameter {v.name!r} is tp-sharded "
                        f"({tp_component(spec)}) but the program was not "
                        f"rewritten for manual tp execution: {hint}. "
                        f"Without the rewrite {what} would compute "
                        f"partial tensor-parallel products without their "
                        f"collectives; the SPMD AllReduce/Reduce "
                        f"strategies also run tp-sharded programs")

    def _apply_tp_shard(self, program: Program) -> Program:
        """Apply tp_shard_pass (cached) when the manual modes will run a
        tp-annotated program on a mesh with a live tp axis: the pass
        splices the explicit tp collectives that make the per-shard step
        compute exactly the single-device math. Kill switch
        PTPU_TP_SHARD=0 skips the rewrite (the gate then rejects)."""
        from ..core import flags
        tpn = self.mesh.axis_size(MODEL_AXIS)
        if (tpn <= 1 or not flags.get_flag("tp_shard")
                or getattr(program, "_tp_applied", False)):
            return program
        from ..framework.sharding import has_tp_annotations
        if not has_tp_annotations(program):
            return program
        key = (id(program), program._version, tpn)
        rewritten = self._tp_cache.get(key)
        if rewritten is None:
            from ..framework.passes import get_pass
            rewritten = get_pass("tp_shard_pass", tp=tpn)(program)
            self._tp_cache[key] = rewritten
        return rewritten

    def _maybe_auto_plan(self, program: Program):
        """BuildStrategy.auto_parallel: run the cost-model-guided planner
        (framework/auto_parallel.py) once per (program version, device
        count, batch) and ADOPT its choice — the chosen BuildStrategy
        knobs and the chosen mesh factorization over this executor's own
        devices. Planning always starts from the USER's base strategy
        (knobs that change numerics — quant_comm, error feedback — are
        pinned to it), so repeated prepares converge instead of
        compounding. Kill switch PTPU_AUTO_PARALLEL=0 (in the compile
        cache key) reverts to the user's own strategy/mesh, so a runtime
        flip recompiles the un-planned configuration."""
        from ..core import flags
        if not getattr(self.build_strategy, "auto_parallel", False):
            return
        if getattr(self, "_auto_plan_suspended", False):
            # replan_on_restore prices the KEPT side through
            # prepare_program; planning here would adopt mid-pricing
            return
        if not flags.get_flag("auto_parallel"):
            orig = getattr(self, "_auto_orig", None)
            if orig is not None and getattr(self, "_auto_adopted", False):
                self.build_strategy, self.mesh = orig
                self._dp = self.mesh.axis_size(DATA_AXIS)
                self._auto_adopted = False
                # forget the plan: flipping the switch back on must
                # RE-plan and re-adopt, and auto_plan_report() must not
                # keep describing a strategy that is no longer executing
                self._auto_plan = None
                self._auto_plan_keys = set()
            return
        if (getattr(program, "_dp_comm_applied", False)
                or getattr(program, "_pp_applied", False)
                or getattr(program, "_memory_plan_applied", False)):
            return   # already-rewritten view: the decision was made
        batch = max((s[0] for s in (self._feed_shapes or {}).values()
                     if len(s) >= 1), default=8)
        key = (id(program), program._version, self.mesh.num_devices,
               int(batch))
        done = getattr(self, "_auto_plan_keys", None)
        if done is None:
            done = self._auto_plan_keys = set()
        # batch None = an elastic-restore decision covering ANY batch
        # (auto_parallel.replan_on_restore priced it against the
        # one-time reshard cost; re-planning here would override it
        # without that price)
        if key in done or key[:3] + (None,) in done:
            return
        from ..framework import auto_parallel as _auto
        if not hasattr(self, "_auto_orig"):
            self._auto_orig = (self.build_strategy, self.mesh)
        base = self._auto_orig[0]
        result = _auto.plan(
            program, self.mesh.num_devices, nominal_batch=int(batch),
            strategy_base=base,
            space=_auto.numerics_preserving_space(base))
        done.add(key)
        self._auto_plan = result
        self.build_strategy = result.strategy
        if dict(result.mesh_axes) != dict(self.mesh.axes):
            devices = list(self.mesh.jax_mesh.devices.flat)
            self.mesh = DeviceMesh(devices, result.mesh_axes)
        self._dp = self.mesh.axis_size(DATA_AXIS)
        self._auto_adopted = True

    def auto_plan_report(self):
        """The adopted PlanResult of the auto-parallel planner — None
        until a prepare ran with BuildStrategy.auto_parallel=True (and
        the PTPU_AUTO_PARALLEL kill switch up)."""
        return getattr(self, "_auto_plan", None)

    def _prepare_program(self, program: Program, scope: Scope) -> Program:
        """BuildStrategy-driven program rewrite, four ordered passes, each
        cached per (program, version, resolved config) and idempotent (the
        base Executor calls this again inside _compile):

        1. tp sharding (tp-annotated params on a tp mesh, manual modes
           only): framework/sharding.py tp_shard_pass splices explicit tp
           collectives so per-shard execution is exact;
        2. explicit gradient comm (ReduceScatter / quant_comm):
           grad_comm.comm_optimize_pass + zero-init of per-replica
           error-feedback state (tp-aware: plans over tp-LOCAL shapes,
           optimizer slices sharded over dp per tp shard);
        3. pipeline partitioning (pipeline_stages >= 2, PTPU_PIPELINE=1):
           passes.pipeline_partition_pass on the (possibly comm-rewritten)
           program — the pp_pipeline_region leaves gradients as LOCAL dp
           partials when dp_grad_comm owns the dp reduction, and pmeans
           them itself otherwise;
        4. static memory plan (memory_plan=True, PTPU_MEMORY_PLAN=1):
           framework/memory_plan.py memory_plan_pass over the program AS
           REWRITTEN — scheduling/coloring/remat decisions are made
           against the ops the step actually runs, and the sanitized
           apply re-verifies the colored program with the r13
           buffer-reuse detectors.

        Step 0, before any of them: the auto-parallel planner
        (BuildStrategy.auto_parallel) may first REPLACE the strategy and
        mesh this executor rewrites FOR (framework/auto_parallel.py)."""
        self._maybe_auto_plan(program)
        return self._apply_memory_plan(
            self._prepare_parallel(program, scope))

    def _apply_memory_plan(self, program: Program) -> Program:
        from ..core import flags
        if (not getattr(self.build_strategy, "memory_plan", False)
                or not flags.get_flag("memory_plan")
                or getattr(program, "_memory_plan_applied", False)):
            return program
        cache = getattr(self, "_plan_cache", None)
        if cache is None:
            cache = self._plan_cache = {}
        batch = max((s[0] for s in (self._feed_shapes or {}).values()
                     if len(s) >= 1), default=8)
        budget_s = float(getattr(self.build_strategy,
                                 "memory_plan_time_budget_s", 0.0) or 0.0)
        prevent_cse = bool(getattr(self.build_strategy,
                                   "memory_plan_prevent_cse", False))
        time_frac = float(getattr(self.build_strategy,
                                  "memory_plan_time_frac", 0.02))
        stash_host = bool(getattr(self.build_strategy,
                                  "memory_plan_stash_to_host", False))
        # every strategy field the plan reads is in the key: BuildStrategy
        # is a mutable dataclass, and a knob flipped between runs must
        # re-plan instead of silently serving the stale plan
        key = (id(program), program._version, int(batch), budget_s,
               prevent_cse, time_frac, stash_host)
        planned = cache.get(key)
        if planned is None:
            from ..framework.passes import get_pass
            planned = get_pass(
                "memory_plan_pass",
                nominal_batch=int(batch),
                time_budget_s=(budget_s or None),
                time_budget_frac=time_frac,
                remat_prevent_cse=prevent_cse,
                stash_to_host=stash_host,
            )(program)
            cache[key] = planned
        return planned

    def _prepare_parallel(self, program: Program, scope: Scope) -> Program:
        if getattr(program, "_pp_applied", False):
            return program
        cfg = _grad_comm.explicit_comm_config(self.build_strategy)
        pcfg = _pipeline.pipeline_config(self.build_strategy)
        if not getattr(program, "_dp_comm_applied", False):
            if cfg is None and pcfg is None:
                # still reconcile: a PREVIOUS explicit-mode config may have
                # left sharded state behind (kill-switch flip back to SPMD)
                self._reconcile_state_placement(program, scope, None)
                return program
            program = self._apply_tp_shard(program)
            if cfg is not None:
                self._gate_manual_mode(
                    program, "the explicit gradient pipeline "
                    "(ReduceScatter / quant_comm)")
                key = (id(program), program._version,
                       tuple(sorted(cfg.items())))
                rewritten = self._comm_cache.get(key)
                if rewritten is None:
                    rewritten = _grad_comm.comm_optimize_pass(
                        program, self._dp, cfg)
                    self._comm_cache[key] = rewritten
                for v in rewritten.global_block().vars.values():
                    if getattr(v, "dp_replica_state", False) \
                            and not scope.has_var(v.name):
                        scope.set_var(v.name, jax.device_put(
                            np.zeros(v.shape, np.float32),
                            self._state_sharding(rewritten, v.name)))
                program = rewritten
        if pcfg is not None:
            program = self._apply_pipeline(program, pcfg)
        marker = ((tuple(sorted(cfg.items())) if cfg else None),
                  (tuple(sorted(pcfg.items())) if pcfg else None),
                  (self.mesh.axis_size(MODEL_AXIS)
                   if getattr(program, "_tp_applied", False) else None))
        self._reconcile_state_placement(
            program, scope,
            marker if marker != (None, None, None) else None)
        return program

    # public views for the elastic checkpoint runtime (parallel/elastic.py)
    # and tooling: the program AS THIS EXECUTOR RUNS IT and the placement
    # its policy assigns a state var — snapshot contents (sharded ZeRO-1
    # accumulators, error-feedback state) and restore-time re-placement
    # must both follow the REWRITTEN view, not the user's program.
    def prepare_program(self, program: Optional[Program] = None,
                        scope: Optional[Scope] = None) -> Program:
        return self._prepare_program(
            program or self.main_program or default_main_program(),
            scope or self.scope)

    def state_sharding(self, program: Program, name: str) -> NamedSharding:
        return self._state_sharding(program, name)

    def _apply_pipeline(self, program: Program, pcfg: Dict) -> Program:
        """Apply pipeline_partition_pass (cached) for the resolved pipeline
        config; validates the mesh carries a pp axis of the right size."""
        enforce(PIPELINE_AXIS in self.mesh.axes
                and self.mesh.axis_size(PIPELINE_AXIS) == pcfg["stages"],
                f"BuildStrategy.pipeline_stages={pcfg['stages']} needs a "
                f"{PIPELINE_AXIS!r} mesh axis of exactly that size; this "
                f"mesh has axes {dict(self.mesh.axes)}",
                exc=InvalidArgumentError)
        self._gate_manual_mode(program, "pipeline-parallel execution")
        key = (id(program), program._version, tuple(sorted(pcfg.items())))
        rewritten = self._pp_cache.get(key)
        if rewritten is None:
            from ..framework.passes import get_pass
            has_dp = DATA_AXIS in self.mesh.axes
            rewritten = get_pass(
                "pipeline_partition_pass",
                num_stages=pcfg["stages"],
                num_microbatches=pcfg["microbatches"],
                schedule=pcfg["schedule"],
                dp_axis=DATA_AXIS if has_dp else "",
                # dp_grad_comm owns the dp reduction when the comm pass ran
                reduce_dp=(has_dp and
                           not getattr(program, "_dp_comm_applied", False)),
            )(program)
            self._pp_cache[key] = rewritten
        return rewritten

    def _reconcile_state_placement(self, program: Program, scope: Scope,
                                   cfg_key):
        """Live state placed under a DIFFERENT comm config (the
        PTPU_QUANT_COMM kill switch flipped, or the strategy's pipeline
        toggled between executors sharing a scope) may sit sharded where
        the new compile expects replicated or vice versa — jit would then
        reject the arg/sharding mismatch. On config change, re-place every
        fully-addressable persistable to the placement this program
        expects. Cross-process arrays are left alone (resharding them is a
        collective; flip the switch before process start in that world)."""
        marks = getattr(self, "_scope_cfg", None)
        if marks is None:
            marks = self._scope_cfg = {}
        if marks.get(id(scope), "<unset>") == cfg_key:
            return
        from ..observability import tracing as _tracing
        with _tracing.span("collective", "parallel/reconcile_state_placement",
                           cfg=str(cfg_key)) as sp:
            moved = 0
            for b in program.blocks:
                for v in b.vars.values():
                    if not v.persistable or not scope.has_var(v.name):
                        continue
                    val = scope.get(v.name)
                    sh = getattr(val, "sharding", None)
                    if sh is None or not getattr(val, "is_fully_addressable",
                                                 True):
                        continue
                    want = self._state_sharding(program, v.name)
                    if not sh.is_equivalent_to(want, getattr(val, "ndim", 0)):
                        scope.set_var(v.name, jax.device_put(val, want))
                        moved += 1
            sp.attrs["moved"] = moved
        marks[id(scope)] = cfg_key

    def _build_step_fn(self, program, feed_names, fetch_names, ro, rw,
                       state_out_names):
        """Manual modes: run the whole step as per-shard SPMD code —
        shard_map full-manual over the mesh — so the dp_grad_comm /
        dp_shard_* ops the comm pass spliced in (r08) and/or the
        pp_pipeline_region schedule engine (r09) can issue their own
        collectives. Feeds arrive as the local dp batch slice, replicated
        over pp; gradients leave the vjp/pipeline region as LOCAL partials
        and cross the wire only through dp_grad_comm (or the region's psum
        when no explicit comm pipeline is configured)."""
        step = super()._build_step_fn(program, feed_names, fetch_names,
                                      ro, rw, state_out_names)
        dp_mode = getattr(program, "_dp_comm_applied", False)
        pp_mode = getattr(program, "_pp_applied", False)
        if not (dp_mode or pp_mode):
            return step
        if pp_mode:
            hidden = getattr(program, "_pp_hidden", frozenset())
            for name in fetch_names:
                enforce(name not in hidden,
                        f"fetch target {name!r} is a forward activation "
                        f"(or a value derived from one — e.g. a pruned "
                        f"metric head) computed inside the pipeline "
                        f"region: its values only ever exist "
                        f"per-microbatch on their stage's device, so "
                        f"pipeline mode can fetch only the loss (and "
                        f"values computed outside the region). Drop the "
                        f"fetch or run without pipeline_stages",
                        exc=InvalidArgumentError)
        has_dp = DATA_AXIS in self.mesh.axes
        has_pp = PIPELINE_AXIS in self.mesh.axes
        has_tp = (MODEL_AXIS in self.mesh.axes
                  and getattr(program, "_tp_applied", False))
        manual_axes = {DATA_AXIS} | ({MODEL_AXIS} if has_tp else set())

        def manual_only(ns: NamedSharding) -> PartitionSpec:
            # manual specs may only name manual axes: keep the dp (and,
            # for tp-rewritten programs, tp) components; everything else
            # becomes None. The r11 full-manual mesh covers dp x pp x tp —
            # sp remains gated out of the manual modes.
            cleaned = []
            for s in ns.spec:
                names = s if isinstance(s, (tuple, list)) else (s,)
                kept = tuple(a for a in names if a in manual_axes)
                if len(kept) == 1:
                    cleaned.append(kept[0])
                elif kept:
                    cleaned.append(kept)
                else:
                    cleaned.append(None)
            return PartitionSpec(*cleaned)

        feed_specs = tuple(manual_only(self._feed_sharding(
            program, n, self._feed_shapes.get(n))) for n in feed_names)
        ro_specs = tuple(manual_only(self._state_sharding(program, n))
                         for n in ro)
        rw_specs = tuple(manual_only(self._state_sharding(program, n))
                         for n in rw)
        state_specs = tuple(manual_only(self._state_sharding(program, n))
                            for n in state_out_names)
        batch_led = self._batch_led_fetches(program, fetch_names)
        fetch_specs = tuple(PartitionSpec(DATA_AXIS) if (led and has_dp)
                            else PartitionSpec() for led in batch_led)
        # fetch contract: non-batch-led fetches come back pmean'd — exact
        # for batch-mean statistics (loss, accuracy), WRONG by 1/dp for a
        # batch sum. Reject the directly-detectable sum fetches instead of
        # silently rescaling them (docs/data_parallel.md).
        if has_dp:
            producers = {n: op.type for blk in program.blocks
                         for op in blk.ops for n in op.output_names()}
            for name, led in zip(fetch_names, batch_led):
                if led:
                    continue
                enforce(producers.get(name) not in ("reduce_sum", "sum"),
                        f"fetch {name!r} is a sum reduction: manual-mode "
                        f"execution returns non-batch-led fetches as "
                        f"the MEAN over data shards, which would silently "
                        f"divide a batch sum by {self._dp}. Fetch a "
                        f"mean-form statistic (or the per-row tensor) "
                        f"instead, or use the SPMD AllReduce/Reduce "
                        f"strategies", exc=InvalidArgumentError)

        def shard_step(dp_idx, pp_idx, tp_idx, feed_vals, ro_vals, rw_vals,
                       seed):
            # dp_idx/pp_idx/tp_idx: local slices of axis-sharded aranges —
            # the shard's indices without a PartitionId instruction
            # (lax.axis_index is rejected by the partitioner inside
            # partial-manual regions)
            idx = dp_idx[0]
            # decorrelate per-shard randomness across dp (dropout masks
            # must differ across batch shards like they do across rows in
            # SPMD mode); pp stages share the seed — the pipeline region
            # re-folds per (microbatch, stage); tp shards ALSO share the
            # seed (they jointly compute ONE logical value)
            seed = seed + idx.astype(jnp.uint32) * np.uint32(2654435761)
            with _grad_comm.dp_index_scope(idx), \
                    _pipeline.pp_index_scope(pp_idx[0]), \
                    _tensor_parallel.tp_index_scope(tp_idx[0]):
                fetches, new_state = step(feed_vals, ro_vals, rw_vals, seed)
            merged = []
            for f, led in zip(fetches, batch_led):
                if led:
                    merged.append(f)   # local rows; out_spec dp reassembles
                elif (has_dp and hasattr(f, "dtype")
                        and jnp.issubdtype(f.dtype, jnp.inexact)):
                    # scalar/statistic fetches are batch means (loss,
                    # accuracy): mean of equal-size shard means == the
                    # global-batch mean. Replicated values pass through
                    # unchanged (pmean of identical copies).
                    merged.append(jax.lax.pmean(f, DATA_AXIS))
                else:
                    merged.append(f)
            return tuple(merged), new_state

        # FULL-manual over every mesh axis. dp/pp partition the batch and
        # the stage chain; tp partitions weights when the tp_shard_pass
        # rewrote the program (its spliced tp_* collectives are the ONLY
        # cross-shard traffic on that axis) and is replicated otherwise;
        # sp stays gated out of the manual modes. Partial-manual (auto=sp)
        # would be the composable form, but this jax/XLA rejects
        # PartitionId and trips manual-subgroup checks inside
        # partial-manual regions.
        dp_spec = PartitionSpec(DATA_AXIS) if has_dp else PartitionSpec()
        pp_spec = PartitionSpec(PIPELINE_AXIS) if has_pp else PartitionSpec()
        tp_spec = (PartitionSpec(MODEL_AXIS)
                   if MODEL_AXIS in self.mesh.axes else PartitionSpec())
        mapped = _shard_map(shard_step, mesh=self.mesh.jax_mesh,
                            in_specs=(dp_spec, pp_spec, tp_spec, feed_specs,
                                      ro_specs, rw_specs, PartitionSpec()),
                            out_specs=(fetch_specs, state_specs),
                            check_vma=False)
        dp = self._dp
        ppn = self.mesh.axis_size(PIPELINE_AXIS)
        tpn = self.mesh.axis_size(MODEL_AXIS)

        def wrapped(feed_vals, ro_vals, rw_vals, seed):
            return mapped(jnp.arange(dp, dtype=jnp.int32),
                          jnp.arange(ppn, dtype=jnp.int32),
                          jnp.arange(tpn, dtype=jnp.int32),
                          feed_vals, ro_vals, rw_vals, seed)

        return wrapped

    def _pad_for_dp(self, program, feed):
        """Make a partial batch runnable: pad every batch-dim feed up to the
        next dp multiple by wrapping real rows (in-distribution values — no
        NaN bait), and zero the padded rows of the batch-row mask so a
        mask-weighted loss counts real rows only (≙ reference
        details/data_balance_op_handle.cc redistributing uneven reader
        batches). Returns (feed, real_rows, padded_rows) — real==padded
        means the feed was already divisible and untouched."""
        from ..framework.program import BATCH_ROW_MASK_NAME

        def _batch_led(name):
            # pad ONLY feeds DECLARED batch-led ([-1, ...]): a fixed-shape
            # auxiliary feed whose dim0 merely equals the batch size must
            # not be wrapped (mirrors _batch_led_fetches on the fetch
            # side). Undeclared feeds (sidecars like @SEQLEN) are batch-led
            # by construction.
            return self._batch_led_feed(program, name)

        sizes = {np.shape(v)[0] for n, v in feed.items()
                 if np.ndim(v) >= 1 and _batch_led(n)}
        if not sizes:
            return feed, None, None
        enforce(len(sizes) == 1,
                f"feed batch dims disagree across vars: {sorted(sizes)} "
                f"(≙ SplitLoDTensor batch split needs one batch size)",
                exc=InvalidArgumentError)
        b = sizes.pop()
        m = getattr(program, "_pp_microbatches", 0)
        if m:
            enforce(b % (self._dp * m) == 0,
                    f"feed batch size {b} is not divisible by "
                    f"dp * num_microbatches = {self._dp} * {m}: the "
                    f"pipeline schedule derives the global-mean loss from "
                    f"EQUAL microbatches on EQUAL dp shards, so "
                    f"wrap-padding would bias it. Feed divisible batches "
                    f"in pipeline mode", exc=InvalidArgumentError)
        if b % self._dp == 0:
            return feed, b, b
        enforce(_grad_comm.explicit_comm_config(self.build_strategy) is None,
                f"feed batch size {b} is not divisible by data-parallel "
                f"degree {self._dp}: the explicit gradient pipeline "
                f"(ReduceScatter / quant_comm) derives the global-mean "
                f"gradient from EQUAL per-shard batches, so wrap-padding "
                f"would bias it. Feed dp-divisible batches in this mode",
                exc=InvalidArgumentError)
        enforce(BATCH_ROW_MASK_NAME in program.global_block().vars,
                f"feed batch size {b} is not divisible by data-parallel "
                f"degree {self._dp}, and the program does not declare "
                f"layers.batch_row_mask() — padding without a mask would "
                f"silently bias an unweighted mean loss (wrapped rows "
                f"counted twice). Either make the batch dp-divisible or "
                f"declare the mask and weight the loss by it "
                f"(loss = reduce_sum(per_ex*mask)/reduce_sum(mask))",
                exc=InvalidArgumentError)
        p = ((b + self._dp - 1) // self._dp) * self._dp
        idx = np.arange(p) % b
        out = {}
        for name, val in feed.items():
            if (np.ndim(val) >= 1 and np.shape(val)[0] == b
                    and _batch_led(name)):
                out[name] = np.take(np.asarray(val), idx, axis=0)
            else:
                out[name] = val
        # a caller-fed mask was wrap-padded above — keep its real-row
        # weights and only zero the rows WE added; synthesize 1/0 otherwise
        if BATCH_ROW_MASK_NAME in out:
            mask = np.asarray(out[BATCH_ROW_MASK_NAME],
                              np.float32).copy()
        else:
            mask = np.ones((p,), np.float32)
        mask[b:] = 0.0
        out[BATCH_ROW_MASK_NAME] = mask
        return out, b, p

    def _batch_led_fetches(self, program, fetch_list):
        """Which fetch targets are declared batch-led ([-1, ...] leading
        dim)? Only those get pad rows stripped — a fetch whose CONCRETE
        leading dim merely coincides with the padded size (e.g. a [16, k]
        parameter) must come back whole."""
        out = []
        for f in fetch_list or []:
            name = f.name if isinstance(f, Variable) else f
            v = self._find_var(program, name)
            shape = getattr(v, "shape", None) if v is not None else None
            out.append(bool(shape) and shape[0] == -1)
        return out

    def _slice_padded_fetches(self, fetches, batch_led, real, stacked=False):
        """Strip pad rows from per-row fetch outputs. `stacked`: run_steps
        fetches carry a leading K (steps) axis; the batch axis is axis 1."""
        out = []
        for f, led in zip(fetches, batch_led):
            if led and hasattr(f, "ndim") and f.ndim >= (2 if stacked else 1):
                out.append(f[:, :real] if stacked else f[:real])
            else:
                out.append(f)
        return out

    # -- scan-fused multi-step loop (run_steps) ---------------------------
    def _shift_scan_axis(self, ns: NamedSharding) -> NamedSharding:
        """Per-step sharding -> stacked sharding: replicated leading K
        (steps) axis. The ONE place the scan-axis placement lives."""
        return NamedSharding(self.mesh.jax_mesh,
                             PartitionSpec(None, *ns.spec))

    def _scan_shardings(self, program, feed_names, fetch_names, ro, rw,
                        state_out_names):
        """Shardings for the run_steps executable: the single-step policy
        (_step_shardings) with the scan axis shifted onto the stacked
        feeds/fetches."""
        ((feed_sh, ro_sh, rw_sh, seed_sh),
         (fetch_sh, state_out_sh)) = self._step_shardings(
            program, feed_names, fetch_names, ro, rw, state_out_names)
        shift = self._shift_scan_axis
        return ((tuple(shift(f) for f in feed_sh), ro_sh, rw_sh, seed_sh),
                (tuple(shift(f) for f in fetch_sh), state_out_sh))

    def run_steps(self, feed_list, fetch_list=None, program=None,
                  scope=None, return_numpy=True):
        """Scan-fused K-step loop over the mesh (see Executor.run_steps);
        each step's feed batch is dp-sharded exactly as in run(). Works
        across processes too: state is globalized first and each stacked
        feed (the K global batches, identical on every process) is placed
        with its scan sharding, each process materializing only its
        addressable shards."""
        program = program or self.main_program or default_main_program()
        scope = scope or self.scope
        if feed_list and feed_list[0]:
            self._feed_shapes = {n: np.shape(v)
                                 for n, v in feed_list[0].items()}
        # rewrite for the explicit gradient pipeline BEFORE any placement
        # decision: _globalize_state/_place_feed_stack consult the
        # rewritten program's markers (sharded accumulators, error state,
        # replicated aux feeds), and the base run_steps would rewrite
        # anyway — doing it here keeps both views identical
        program = self._prepare_program(program, scope)
        enforce(len(feed_list) >= 1, "run_steps needs at least one feed",
                exc=InvalidArgumentError)
        padded_list = []
        real_b = padded_b = None
        for f in feed_list:
            f2, rb, pb = self._pad_for_dp(program, dict(f))
            padded_list.append(f2)
            real_b, padded_b = rb, pb  # uniform: signatures must match
        feed_list = padded_list
        self._feed_shapes = {n: np.shape(v)
                             for n, v in feed_list[0].items()}
        if self._spans_processes():
            self._globalize_state(program, scope)
        fetches = super().run_steps(feed_list, fetch_list=fetch_list,
                                    program=program, scope=scope,
                                    return_numpy=return_numpy)
        if real_b is not None and padded_b != real_b:
            # stacked fetches are [K, batch, ...]: strip pad rows on axis 1
            fetches = self._slice_padded_fetches(
                fetches, self._batch_led_fetches(program, fetch_list),
                real_b, stacked=True)
        return fetches

    def _place_feed_stack(self, program, name, vals):
        """Stack K per-step feed values; in a cross-process world place the
        (identical-on-every-process) host stack with its scan sharding so
        each process materializes only its addressable shards. Local runs
        keep the base (device-side) stacking — no host round trip."""
        if not self._spans_processes():
            return super()._place_feed_stack(program, name, vals)
        for v in vals:
            sh = getattr(v, "sharding", None)
            if sh is not None and not sh.is_fully_addressable:
                raise NotImplementedError(
                    f"run_steps feed {name!r} is already a global array; "
                    f"feed host values (the global batch, identical on "
                    f"every process) or use per-step run() for "
                    f"pre-placed feeds")
        stack = np.stack([np.asarray(v) for v in vals])
        return jax.device_put(
            stack,
            self._shift_scan_axis(self._feed_sharding(
                program, name, self._feed_shapes.get(name))))

    # -- multi-process state/feed placement -------------------------------
    def _spans_processes(self) -> bool:
        return jax.process_count() > 1

    def _globalize_state(self, program: Program, scope: Scope):
        """Place persistable state onto the global mesh (≙
        BCastParamsToDevices, reference parallel_executor.cc:210): after a
        plain Executor ran the startup program, state lives as
        process-local arrays; a cross-process mesh needs it as global
        arrays. Every process computed IDENTICAL host values (seeded
        startup program), so placement is a device_put of the host value
        with the state's global sharding — each process materializes only
        its addressable shards. Runs once per (program version, scope):
        afterwards every state output of the compiled step is already
        global."""
        from ..io import _is_persistable, _select_vars
        from ..observability import tracing as _tracing
        key = (id(program), program._version, id(scope))
        if key in getattr(self, "_globalized", ()):
            return
        with _tracing.span("collective", "parallel/globalize_state"):
            for v in _select_vars(program, _is_persistable):
                if not scope.has_var(v.name):
                    continue
                val = scope.get(v.name)
                sh = getattr(val, "sharding", None)
                if sh is not None and not sh.is_fully_addressable:
                    continue  # already a global array
                target = self._state_sharding(program, v.name)
                scope.set_var(v.name, jax.device_put(np.asarray(val), target))
            self._globalized = getattr(self, "_globalized", set()) | {key}

    # -- host-offload optimizer state (framework/offload.py) ---------------
    def _host_optimizer_state(self, program, scope):
        """Lazily build (and cache per program/scope identity) the
        HostOptimizerState for this step, or None when the knob is off,
        the PTPU_OFFLOAD=0 kill switch is up, or the program carries no
        optimizer accumulators yet (eval/startup programs)."""
        import os
        if not getattr(self.build_strategy, "offload_optimizer_state",
                       False):
            return None
        if os.environ.get("PTPU_OFFLOAD", "1") == "0":
            return None
        from ..framework import offload as _offload
        key = (id(program), getattr(program, "_version", 0), id(scope))
        if getattr(self, "_host_opt_key", None) == key:
            return self._host_opt
        names = _offload.optimizer_state_names(program, scope)
        if not names:
            return None
        prev = getattr(self, "_host_opt", None)
        if prev is not None:
            # program/scope changed under us: bring the old shards home
            # and return their buffers before re-keying
            prev.restore()
            prev.release()
        self._host_opt = _offload.HostOptimizerState(scope, names)
        self._host_opt_key = key
        return self._host_opt

    # -- run --------------------------------------------------------------
    def run(self,
            fetch_list: Optional[Sequence[Union[str, Variable]]] = None,
            feed: Optional[Dict[str, Any]] = None,
            program: Optional[Program] = None,
            scope: Optional[Scope] = None,
            return_numpy: bool = True):
        """≙ ParallelExecutor.run (reference parallel_executor.py:168).
        Argument order follows the reference (fetch_list first)."""
        program = program or self.main_program or default_main_program()
        scope = scope or self.scope
        # provisional feed shapes BEFORE the rewrite: the memory planner's
        # nominal batch reads them (padded shapes re-stash below)
        if feed:
            self._feed_shapes = {n: np.shape(v) for n, v in feed.items()}
        # see run_steps: placement below must read the REWRITTEN program
        program = self._prepare_program(program, scope)
        # ZeRO-offload: the accumulator shards live on the host between
        # steps — h2d them back BEFORE placement/dispatch, d2h them out
        # after the fetches return (the d2h overlaps whatever the host
        # does next; costs.predict's `offload` section prices whether
        # the round-trip hides behind the step)
        host_opt = self._host_optimizer_state(program, scope)
        if host_opt is not None:
            host_opt.restore()
        feed, real_b, padded_b = self._pad_for_dp(program, dict(feed or {}))
        # synthesize the batch-row mask BEFORE multi-process placement: the
        # base Executor would otherwise inject a host numpy array after the
        # _place loop, which jit cannot auto-place onto a non-addressable
        # global sharding
        feed = self._synthesize_batch_mask(program, feed)
        # stash shapes so _compile can build feed shardings without
        # re-plumbing the Executor.run signature.
        self._feed_shapes = {n: np.shape(v) for n, v in feed.items()}
        if self._spans_processes():
            self._globalize_state(program, scope)
            # feeds carry the GLOBAL batch (identical on every process —
            # the reference's nccl2-mode trainers likewise each construct
            # their portion deterministically); device_put materializes
            # each process's addressable shards of the dp split. Values
            # that are ALREADY global jax arrays (e.g. built with
            # make_array_from_process_local_data for per-process-distinct
            # data) pass through untouched.
            def _place(n, v):
                sh = getattr(v, "sharding", None)
                if sh is not None and not sh.is_fully_addressable:
                    return v
                return jax.device_put(
                    np.asarray(v),
                    self._feed_sharding(program, n, np.shape(v)))
            feed = {n: _place(n, v) for n, v in feed.items()}
        fetches = super().run(program=program, feed=feed,
                              fetch_list=fetch_list, scope=scope,
                              return_numpy=return_numpy)
        if host_opt is not None:
            host_opt.offload()
        if real_b is not None and padded_b != real_b:
            fetches = self._slice_padded_fetches(
                fetches, self._batch_led_fetches(program, fetch_list),
                real_b)
        return fetches

    def cost_report(self, program: Optional[Program] = None,
                    scope: Optional[Scope] = None,
                    nominal_batch: int = 8) -> Dict:
        """framework.costs.predict() over the program AS THIS EXECUTOR
        RUNS IT (after the tp/dp-comm/pipeline rewrites), with the mesh's
        dp/tp degrees filled in — the prediction side of the r12 cost
        ledger (observability/ledger.py)."""
        from ..framework import costs as _costs
        program = program or self.main_program or default_main_program()
        scope = scope or self.scope
        rewritten = self._prepare_program(program, scope)
        return _costs.predict(rewritten, self.build_strategy,
                              dp=self._dp,
                              tp=self.mesh.axis_size(MODEL_AXIS),
                              nominal_batch=nominal_batch)

    def memory_report(self, feed, program: Optional[Program] = None,
                      scope: Optional[Scope] = None,
                      nominal_batch: int = 8) -> Dict:
        """Predicted + measured memory for the program AS RUN, in one
        dict — the memory half of the r17 sensor pair (ROADMAP items 1
        and 2 read both sides):

          predicted  cost_report()["memory"] — the static estimate plus
                     the per-device category buckets
                     (costs.memory_categories at this mesh's dp/tp)
          measured   Executor.memory_census() — actual scope arrays, the
                     XLA executable's buffer-assignment figures, the
                     live-array sweep

        Run the step once first (the census measures the executable the
        runs actually use); observability/ledger.py
        check_memory_identity reconciles the two sides with the
        accounting identity."""
        report = self.cost_report(program=program, scope=scope,
                                  nominal_batch=nominal_batch)
        census = self.memory_census(feed, program=program, scope=scope)
        return {"predicted": report["memory"], "measured": census}

    @property
    def device_count(self) -> int:
        return self.mesh.num_devices
