"""ParallelExecutor: SPMD data-parallel program execution.

≙ reference framework/parallel_executor.cc:119 + python/paddle/fluid/
parallel_executor.py:32. The reference replicates block-0 onto every GPU,
inserts NCCL all-reduce op handles per gradient, and schedules the SSA graph
with a thread pool. The TPU-native design compiles the SAME single-device
program once under `jax.jit` with sharding annotations:

- feed tensors are sharded along dim 0 over the mesh's data axis
  (≙ FeedAndSplitTensorIntoLocalScopes / SplitLoDTensor,
  parallel_executor.cc:333);
- parameters are replicated (≙ BCastParamsToDevices, :210);
- XLA's SPMD partitioner then emits the per-gradient all-reduce on ICI that
  the reference builds explicitly (multi_devices_graph_pass.cc:419-425);
- with `ReduceStrategy.Reduce`, optimizer accumulators are sharded across
  the data axis instead — XLA lowers the update to reduce-scatter + sharded
  optimizer math + all-gather, the ZeRO-1 formulation of the reference's
  reduce-to-one-owner-then-broadcast mode (:412-418,445-453).

Because the mean loss is computed over the *global* (sharded) batch, loss
scaling by 1/num_devices (≙ ScaleLossGradOpHandle) is implicit.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..core.enforce import InvalidArgumentError, enforce
from ..framework.executor import Executor
from ..framework.program import Program, Variable, default_main_program
from ..framework.scope import Scope, global_scope
from .mesh import DATA_AXIS, SEQUENCE_AXIS, DeviceMesh, get_default_mesh
from .strategy import (BuildStrategy, ExecutionStrategy,
                       GradientScaleStrategy, ReduceStrategy)


class ParallelExecutor(Executor):
    """Drop-in multi-device executor (≙ fluid.ParallelExecutor)."""

    def __init__(self,
                 use_tpu: bool = True,
                 loss_name: Optional[str] = None,
                 main_program: Optional[Program] = None,
                 share_vars_from: Optional["ParallelExecutor"] = None,
                 exec_strategy: Optional[ExecutionStrategy] = None,
                 build_strategy: Optional[BuildStrategy] = None,
                 num_trainers: int = 1,
                 trainer_id: int = 0,
                 scope: Optional[Scope] = None,
                 mesh: Optional[DeviceMesh] = None):
        super().__init__()
        self.mesh = mesh or get_default_mesh()
        self.loss_name = loss_name
        self.main_program = main_program
        self.exec_strategy = exec_strategy or ExecutionStrategy()
        self.build_strategy = build_strategy or BuildStrategy()
        self.scope = scope or global_scope()
        if share_vars_from is not None:
            self.scope = share_vars_from.scope
        self._dp = self.mesh.axis_size(DATA_AXIS)
        self._feed_shapes: Dict[str, tuple] = {}
        if (self.build_strategy.gradient_scale_strategy
                == GradientScaleStrategy.CoeffNumDevice):
            raise NotImplementedError(
                "GradientScaleStrategy.CoeffNumDevice is not implemented: "
                "under SPMD the global-batch `mean` already scales the loss "
                "gradient; build the program with a mean-reduced loss "
                "(GradientScaleStrategy.One) instead")

    # -- sharding assignment ---------------------------------------------
    def _find_var(self, program: Program, name: str) -> Optional[Variable]:
        for b in program.blocks:
            if b.has_var(name):
                return b.var(name)
        return None

    def _state_sharding(self, program: Program, name: str) -> NamedSharding:
        v = self._find_var(program, name)
        spec = getattr(v, "sharding_spec", None) if v is not None else None
        if spec is not None:
            # explicit TP/EP placement from ParamAttr(sharding_spec=...) or
            # parallel.auto_shard annotation; mesh.sharding drops axis names
            # not present in this mesh (replicated there).
            return self.mesh.sharding(*spec)
        if (self.build_strategy.reduce_strategy == ReduceStrategy.Reduce
                and v is not None
                and getattr(v, "is_optimizer_state", False)
                and v.shape and len(v.shape) >= 1
                and v.shape[0] >= self._dp and v.shape[0] % self._dp == 0):
            # ZeRO-1: shard the accumulator's dim 0 across the data axis.
            return self.mesh.sharding(DATA_AXIS,
                                      *([None] * (len(v.shape) - 1)))
        return self.mesh.replicated()

    def _feed_sharding(self, program: Program, name: str,
                       shape) -> NamedSharding:
        if not shape:  # scalar feed
            return self.mesh.replicated()
        if (self.build_strategy.enable_sequence_parallel and len(shape) >= 2):
            v = self._find_var(program, name)
            if v is not None and getattr(v, "lod_level", 0) > 0:
                # sequence feed [B, T, ...]: split T over the sequence axis
                # too (context parallelism; ring attention consumes this
                # layout — parallel/ring_attention.py).
                return self.mesh.sharding(DATA_AXIS, SEQUENCE_AXIS,
                                          *([None] * (len(shape) - 2)))
        return self.mesh.sharding(DATA_AXIS, *([None] * (len(shape) - 1)))

    # -- compile with shardings ------------------------------------------
    def _step_shardings(self, program, feed_names, fetch_names, ro, rw,
                        state_out_names):
        """The ONE place per-name placement policy lives: shardings for a
        single step's (feeds, ro, rw, seed) inputs and (fetches, state)
        outputs — both the single-step compile and the scan-fused
        run_steps derive from it."""
        feed_shard = tuple(self._feed_sharding(program, n,
                                               self._feed_shapes.get(n))
                           for n in feed_names)
        ro_shard = tuple(self._state_sharding(program, n) for n in ro)
        rw_shard = tuple(self._state_sharding(program, n) for n in rw)
        repl = self.mesh.replicated()
        fetch_shard = tuple(repl for _ in fetch_names)
        state_out_shard = tuple(self._state_sharding(program, n)
                                for n in state_out_names)
        return ((feed_shard, ro_shard, rw_shard, repl),
                (fetch_shard, state_out_shard))

    def _compile(self, program: Program, scope: Scope, feed_names, fetch_names,
                 in_shardings=None, out_shardings=None, analysis=None):
        analysis = analysis or self._analyze_state(program, scope, feed_names,
                                                   fetch_names)
        ro, rw, out_only = analysis
        state_out_names = sorted(set(rw) | set(out_only))
        in_sh, out_sh = self._step_shardings(program, feed_names,
                                             fetch_names, ro, rw,
                                             state_out_names)
        return super()._compile(
            program, scope, feed_names, fetch_names,
            in_shardings=in_sh, out_shardings=out_sh, analysis=analysis)

    def _check_dp_divisible(self, feed):
        for name, val in feed.items():
            if np.ndim(val) >= 1:
                bs = np.shape(val)[0]
                enforce(bs % self._dp == 0,
                        f"feed var {name!r} batch size {bs} is not divisible "
                        f"by data-parallel degree {self._dp} "
                        f"(≙ SplitLoDTensor batch split)",
                        exc=InvalidArgumentError)

    # -- scan-fused multi-step loop (run_steps) ---------------------------
    def _shift_scan_axis(self, ns: NamedSharding) -> NamedSharding:
        """Per-step sharding -> stacked sharding: replicated leading K
        (steps) axis. The ONE place the scan-axis placement lives."""
        return NamedSharding(self.mesh.jax_mesh,
                             PartitionSpec(None, *ns.spec))

    def _scan_shardings(self, program, feed_names, fetch_names, ro, rw,
                        state_out_names):
        """Shardings for the run_steps executable: the single-step policy
        (_step_shardings) with the scan axis shifted onto the stacked
        feeds/fetches."""
        ((feed_sh, ro_sh, rw_sh, seed_sh),
         (fetch_sh, state_out_sh)) = self._step_shardings(
            program, feed_names, fetch_names, ro, rw, state_out_names)
        shift = self._shift_scan_axis
        return ((tuple(shift(f) for f in feed_sh), ro_sh, rw_sh, seed_sh),
                (tuple(shift(f) for f in fetch_sh), state_out_sh))

    def run_steps(self, feed_list, fetch_list=None, program=None,
                  scope=None, return_numpy=True):
        """Scan-fused K-step loop over the mesh (see Executor.run_steps);
        each step's feed batch is dp-sharded exactly as in run(). Works
        across processes too: state is globalized first and each stacked
        feed (the K global batches, identical on every process) is placed
        with its scan sharding, each process materializing only its
        addressable shards."""
        program = program or self.main_program or default_main_program()
        scope = scope or self.scope
        enforce(len(feed_list) >= 1, "run_steps needs at least one feed",
                exc=InvalidArgumentError)
        self._check_dp_divisible(feed_list[0])
        self._feed_shapes = {n: np.shape(v)
                             for n, v in feed_list[0].items()}
        if self._spans_processes():
            self._globalize_state(program, scope)
        return super().run_steps(feed_list, fetch_list=fetch_list,
                                 program=program, scope=scope,
                                 return_numpy=return_numpy)

    def _place_feed_stack(self, program, name, vals):
        """Stack K per-step feed values; in a cross-process world place the
        (identical-on-every-process) host stack with its scan sharding so
        each process materializes only its addressable shards. Local runs
        keep the base (device-side) stacking — no host round trip."""
        if not self._spans_processes():
            return super()._place_feed_stack(program, name, vals)
        for v in vals:
            sh = getattr(v, "sharding", None)
            if sh is not None and not sh.is_fully_addressable:
                raise NotImplementedError(
                    f"run_steps feed {name!r} is already a global array; "
                    f"feed host values (the global batch, identical on "
                    f"every process) or use per-step run() for "
                    f"pre-placed feeds")
        stack = np.stack([np.asarray(v) for v in vals])
        return jax.device_put(
            stack,
            self._shift_scan_axis(self._feed_sharding(
                program, name, self._feed_shapes.get(name))))

    # -- multi-process state/feed placement -------------------------------
    def _spans_processes(self) -> bool:
        return jax.process_count() > 1

    def _globalize_state(self, program: Program, scope: Scope):
        """Place persistable state onto the global mesh (≙
        BCastParamsToDevices, reference parallel_executor.cc:210): after a
        plain Executor ran the startup program, state lives as
        process-local arrays; a cross-process mesh needs it as global
        arrays. Every process computed IDENTICAL host values (seeded
        startup program), so placement is a device_put of the host value
        with the state's global sharding — each process materializes only
        its addressable shards. Runs once per (program version, scope):
        afterwards every state output of the compiled step is already
        global."""
        from ..io import _is_persistable, _select_vars
        key = (id(program), program._version, id(scope))
        if key in getattr(self, "_globalized", ()):
            return
        for v in _select_vars(program, _is_persistable):
            if not scope.has_var(v.name):
                continue
            val = scope.get(v.name)
            sh = getattr(val, "sharding", None)
            if sh is not None and not sh.is_fully_addressable:
                continue  # already a global array
            target = self._state_sharding(program, v.name)
            scope.set_var(v.name, jax.device_put(np.asarray(val), target))
        self._globalized = getattr(self, "_globalized", set()) | {key}

    # -- run --------------------------------------------------------------
    def run(self,
            fetch_list: Optional[Sequence[Union[str, Variable]]] = None,
            feed: Optional[Dict[str, Any]] = None,
            program: Optional[Program] = None,
            scope: Optional[Scope] = None,
            return_numpy: bool = True):
        """≙ ParallelExecutor.run (reference parallel_executor.py:168).
        Argument order follows the reference (fetch_list first)."""
        program = program or self.main_program or default_main_program()
        scope = scope or self.scope
        feed = dict(feed or {})
        self._check_dp_divisible(feed)
        # stash shapes so _compile can build feed shardings without
        # re-plumbing the Executor.run signature.
        self._feed_shapes = {n: np.shape(v) for n, v in feed.items()}
        if self._spans_processes():
            self._globalize_state(program, scope)
            # feeds carry the GLOBAL batch (identical on every process —
            # the reference's nccl2-mode trainers likewise each construct
            # their portion deterministically); device_put materializes
            # each process's addressable shards of the dp split. Values
            # that are ALREADY global jax arrays (e.g. built with
            # make_array_from_process_local_data for per-process-distinct
            # data) pass through untouched.
            def _place(n, v):
                sh = getattr(v, "sharding", None)
                if sh is not None and not sh.is_fully_addressable:
                    return v
                return jax.device_put(
                    np.asarray(v),
                    self._feed_sharding(program, n, np.shape(v)))
            feed = {n: _place(n, v) for n, v in feed.items()}
        return super().run(program=program, feed=feed, fetch_list=fetch_list,
                           scope=scope, return_numpy=return_numpy)

    @property
    def device_count(self) -> int:
        return self.mesh.num_devices
