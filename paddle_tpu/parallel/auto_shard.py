"""Automatic TP/EP sharding annotation for transformer-family programs.

NEW capability (no reference analogue — SURVEY.md §2.3 confirms the reference
has no tensor parallelism). Applies the Megatron recipe by parameter-name
pattern over a built program: attention qkv and MLP up-proj weights are
column-parallel (last dim over `tp`), attention out-proj and MLP down-proj
are row-parallel (first matmul dim over `tp`), embedding tables are
vocab-row-sharded (the distributed-lookup-table / EP analogue, reference
distribute_transpiler.py:212). ParallelExecutor reads the resulting
``sharding_spec`` attributes; XLA's SPMD partitioner inserts the collectives.
"""

from __future__ import annotations

import re
from typing import Dict, Optional, Sequence, Tuple

from ..framework.program import Program
from .mesh import MODEL_AXIS

# (regex over parameter name) -> spec builder taking ndim
_COLUMN = lambda nd: tuple([None] * (nd - 1) + [MODEL_AXIS])     # noqa: E731
_ROW = lambda nd: tuple([None] * (nd - 2) + [MODEL_AXIS, None])  # noqa: E731
_VOCAB = lambda nd: tuple([MODEL_AXIS] + [None] * (nd - 1))      # noqa: E731

DEFAULT_RULES: Sequence[Tuple[str, object]] = (
    (r"(_q|_k|_v|_qkv|_fc1|_up|_gate)(\.w|\.b)?(_\d+)?$", _COLUMN),
    (r"(_o|_out|_fc2|_down)(\.w)(_\d+)?$", _ROW),
    (r"(_emb|_embedding|emb\.w|lm_head\.w)(_\d+)?$", _VOCAB),
)


def annotate_tp(program: Optional[Program] = None,
                rules: Sequence[Tuple[str, object]] = DEFAULT_RULES,
                verbose: bool = False) -> Dict[str, tuple]:
    """Set ``sharding_spec`` on matching parameters of `program`.
    Returns {param_name: spec} for what was annotated."""
    from ..framework.program import default_main_program
    program = program or default_main_program()
    annotated = {}
    for block in program.blocks:
        for v in block.vars.values():
            if not getattr(v, "trainable", False) or v.shape is None:
                continue
            for pat, builder in rules:
                if re.search(pat, v.name):
                    if builder in (_ROW, _VOCAB) and len(v.shape) < 2:
                        # biases of row-parallel/vocab-sharded layers
                        # replicate (a [V] lm-head bias adds to logits the
                        # row-parallel psum already made replicated)
                        continue
                    spec = builder(len(v.shape))
                    v.sharding_spec = spec
                    annotated[v.name] = spec
                    break
    return annotated
