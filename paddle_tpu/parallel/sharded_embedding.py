"""Sharded embedding tables (expert/vocab partitioning).

≙ reference distributed lookup table (SURVEY.md §2.3: huge embeddings sharded
across pservers, trainer prefetches rows by id — prefetch_op.cc,
lookup_sparse_table_op.cc, distribute_transpiler.py:212). TPU-native design:
the table lives sharded over a mesh axis (rows split); lookups run under
shard_map — each device gathers the ids that fall in its row range and the
partial results are psum-combined (an all-to-all-free formulation that XLA
maps well to ICI; masked-gather cost is O(ids) per device).

The backward pass through jnp.take is a scatter-add onto the local shard,
which XLA keeps sharded — the gradient never materializes the full table
(the SelectedRows sparse-grad capability, reference selected_rows.h:32).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .mesh import MODEL_AXIS, DeviceMesh, shard_map


def sharded_embedding_lookup(mesh: DeviceMesh, table, ids,
                             axis_name: str = MODEL_AXIS):
    """table: [V, D] (will be row-sharded over `axis_name`); ids: int [...].
    Returns [..., D]."""
    n = mesh.axis_size(axis_name)
    v, d = table.shape
    assert v % n == 0, f"vocab {v} not divisible by shard count {n}"
    rows_per = v // n

    def body(tbl, ids):
        idx = jax.lax.axis_index(axis_name)
        lo = idx * rows_per
        local = ids - lo
        in_range = (local >= 0) & (local < rows_per)
        safe = jnp.clip(local, 0, rows_per - 1)
        vals = jnp.take(tbl, safe, axis=0)
        vals = jnp.where(in_range[..., None], vals, 0.0)
        return jax.lax.psum(vals, axis_name)

    f = shard_map(body, mesh=mesh.jax_mesh,
                  in_specs=(P(axis_name, None), P()),
                  out_specs=P())
    return f(table, ids)


def embedding_table_sharding(mesh: DeviceMesh, axis_name: str = MODEL_AXIS):
    """NamedSharding to place/keep a [V, D] table row-sharded."""
    return mesh.sharding(axis_name, None)
