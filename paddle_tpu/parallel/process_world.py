"""Simulated multi-process world: N in-process ranks over one filesystem.

jaxlib 0.4.x in this container cannot form a real jax.distributed process
world (standing r08 caveat), but the chief-commits checkpoint barrier
(parallel/elastic.py) is a protocol over *processes*, not over devices —
what it needs from the runtime is small and simulable exactly:

- **ranks**: N participants with stable integer identities, one of which
  is the chief; each runs the same per-rank protocol function on its own
  thread (real concurrency: stragglers, reordered acks, and deadline
  races are all real, not mocked);
- **rank-private staging directories**: every rank stages its shard files
  in a directory only it writes (`.tmp-<serial>-rank<r>`), the on-disk
  shape of a per-host local write in a real multi-host world;
- **a message channel**: per-rank inboxes with blocking receive +
  deadline — the ack/commit/abort control plane;
- **per-rank fault injection**: `PTPU_FAULT_INJECT` grows world-aware
  directives so a test can kill, drop, or delay EXACTLY one rank at
  EXACTLY one protocol phase:

      crash_rank:<r>@<phase>[@<offset>]   REAL SIGKILL of the hosting
                                          process the moment rank r
                                          reaches <phase>; with <offset>
                                          (stage phase only) the rank's
                                          staged payload is first
                                          truncated at that byte offset,
                                          so the disk looks exactly as if
                                          the writer died mid-write
      drop_rank:<r>@<phase>               SIMULATED death: rank r stops
                                          participating at <phase> (its
                                          thread exits; no ack is ever
                                          sent) while the rest of the
                                          world keeps running — the
                                          chief's deadline must handle it
      straggle_rank:<r>@<phase>@<secs>    rank r sleeps <secs> at <phase>
                                          (exercises the barrier
                                          deadline without killing)

The protocol phases (the crash matrix of the property test, one column
per entry of `PHASES`):

      stage    rank writes + fsyncs its shard container/manifest
      ack      staged files are durable; digest manifest not yet sent
      barrier  chief collected the LAST ack; nothing renamed yet
      commit   staging renamed into place; COMMIT marker not yet written
      post     COMMIT marker durable

Because all ranks share one OS process here, a `crash_rank` SIGKILL
takes the whole world down at that instant — a strictly RICHER set of
torn on-disk states than a single-rank death (every other rank is at an
arbitrary point of its own phase), and every one of them must satisfy
the commit protocol's atomicity property. `drop_rank` covers the other
half: a single death the surviving world must tolerate. Structure-pinned
for hardware: on a real multi-host deployment each rank is a process,
`send`/`recv` ride the coordination service, and nothing else changes.
"""

from __future__ import annotations

import os
import queue
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core import flags
from ..core.enforce import InvalidArgumentError, enforce

#: protocol phases a fault directive may name, in protocol order
PHASES = ("stage", "ack", "barrier", "commit", "post")

#: world-aware PTPU_FAULT_INJECT directives (parsed here, not by
#: elastic.fault_injection_config — their values are structured, not floats)
WORLD_DIRECTIVES = ("crash_rank", "drop_rank", "straggle_rank")


class RankDead(BaseException):
    """Simulated death of one rank (drop_rank): unwinds the rank's
    thread without running any more of its protocol. BaseException so a
    protocol-level `except Exception` cannot accidentally resurrect a
    dead rank."""

    def __init__(self, rank: int, phase: str):
        super().__init__(f"rank {rank} dropped at phase {phase!r}")
        self.rank = rank
        self.phase = phase


def _parse_world_directive(name: str, val: str) -> Tuple[int, str, Optional[float]]:
    """`<rank>@<phase>[@<number>]` — shared shape of all three world
    directives."""
    parts = val.split("@")
    enforce(2 <= len(parts) <= 3,
            f"PTPU_FAULT_INJECT {name} wants <rank>@<phase>[@<value>], "
            f"got {val!r}", exc=InvalidArgumentError)
    enforce(parts[1] in PHASES,
            f"PTPU_FAULT_INJECT {name}: unknown phase {parts[1]!r} "
            f"(one of {PHASES})", exc=InvalidArgumentError)
    try:
        rank = int(parts[0])
        extra = float(parts[2]) if len(parts) == 3 else None
    except ValueError as e:
        raise InvalidArgumentError(
            f"PTPU_FAULT_INJECT {name}: {val!r} — rank must be an "
            f"integer and the trailing value a number "
            f"(<rank>@<phase>[@<value>])") from e
    return rank, parts[1], extra


def world_fault_plan(raw: Optional[str] = None) -> Dict[str, Dict[int, tuple]]:
    """Parse the world-aware directives out of PTPU_FAULT_INJECT.

    Returns {"crash": {rank: (phase, offset|None)},
             "drop":  {rank: (phase, None)},
             "straggle": {rank: (phase, seconds)}}.
    Non-world directives (crash_at_step, crash_mid_save, slow_writer) are
    ignored here — elastic.fault_injection_config owns those."""
    if raw is None:
        raw = os.environ.get("PTPU_FAULT_INJECT", "")
    plan: Dict[str, Dict[int, tuple]] = {"crash": {}, "drop": {},
                                         "straggle": {}}
    for part in raw.split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        name, val = part.split(":", 1)
        if name not in WORLD_DIRECTIVES:
            continue
        rank, phase, extra = _parse_world_directive(name, val)
        if name == "crash_rank":
            plan["crash"][rank] = (phase, extra)
        elif name == "drop_rank":
            plan["drop"][rank] = (phase, None)
        else:
            enforce(extra is not None,
                    "PTPU_FAULT_INJECT straggle_rank wants "
                    "<rank>@<phase>@<seconds>", exc=InvalidArgumentError)
            plan["straggle"][rank] = (phase, extra)
    return plan


def _sigkill_self():  # pragma: no cover - the process dies here
    os.kill(os.getpid(), signal.SIGKILL)


def _truncate_payload_at(dirname: str, offset: int):
    """One shared copy of the crash-offset accounting
    (sharded_checkpoint.truncate_payload_at, also behind elastic's
    crash_mid_save); an offset beyond the payload leaves the files
    whole — the kill still happens at the phase boundary."""
    from ..sharded_checkpoint import truncate_payload_at
    truncate_payload_at(dirname, offset)


class ProcessWorld:
    """N simulated ranks with per-rank inboxes and fault hooks.

    One instance models one gang of training processes. The barrier
    protocol (elastic.py) is written against exactly this surface:

        world.send(src, dst, kind, **payload)
        msg = world.recv(rank, timeout=...)      # None on timeout
        world.fault(rank, phase, staging=...)    # fault-injection point
        results = world.run(fn)                  # fn(rank) on every rank

    `run` executes `fn` on one thread per LIVE rank and returns the
    per-rank results (`None` for a dropped/failed rank, with the
    exception kept in `world.failures`). Ranks dropped by a fault stay
    dead for the lifetime of the world — a later `run` (the next
    snapshot attempt) proceeds without them, exactly like a real gang
    missing one process."""

    #: monotone world ids within a process (two worlds in one test must
    #: not merge their trace lanes)
    _ID_SEQ = __import__("itertools").count(1)

    def __init__(self, world_size: int, chief: int = 0):
        enforce(world_size >= 1, "world_size must be >= 1",
                exc=InvalidArgumentError)
        enforce(0 <= chief < world_size,
                f"chief rank {chief} outside world of {world_size}",
                exc=InvalidArgumentError)
        self.world_size = world_size
        self.chief = chief
        #: stable identity stamped (with rank/world_size) onto every
        #: span a rank thread records — the {world_id, rank, world_size}
        #: triple tools/trace_merge.py lanes the merged timeline by
        self.world_id = f"pw{os.getpid()}-{next(self._ID_SEQ)}"
        #: serializes barrier rounds over this world (elastic.py): two
        #: concurrent rounds would steal each other's acks off the
        #: chief's inbox
        self.barrier_lock = threading.Lock()
        self._inboxes: List[queue.Queue] = [queue.Queue()
                                            for _ in range(world_size)]
        #: ranks that died (drop_rank or an exception escaping fn)
        self.dead: set = set()
        #: rank -> exception from the last run()
        self.failures: Dict[int, BaseException] = {}
        self._fault_plan = None

    # -- membership -------------------------------------------------------
    def is_chief(self, rank: int) -> bool:
        return rank == self.chief

    def live_ranks(self) -> List[int]:
        return [r for r in range(self.world_size) if r not in self.dead]

    # -- message channel --------------------------------------------------
    def send(self, src: int, dst: int, kind: str, **payload):
        """Enqueue a message into dst's inbox. Sends from/to dead ranks
        are dropped silently — a real dead process neither sends nor
        receives, and the protocol must survive that, not error on it."""
        if src in self.dead or dst in self.dead:
            return
        self._inboxes[dst].put({"kind": kind, "src": src, **payload})

    def recv(self, rank: int, timeout: Optional[float] = None
             ) -> Optional[Dict[str, Any]]:
        """Blocking receive with deadline; returns None on timeout (the
        barrier's straggler branch) — never raises."""
        try:
            return self._inboxes[rank].get(timeout=timeout)
        except queue.Empty:
            return None

    def drain(self, rank: int):
        """Discard every queued message for `rank` (a fresh protocol
        round must not consume a stale ack from an aborted one)."""
        try:
            while True:
                self._inboxes[rank].get_nowait()
        except queue.Empty:
            pass

    # -- fault injection --------------------------------------------------
    def fault(self, rank: int, phase: str,
              staging: Optional[str] = None,
              serial: Optional[int] = None):
        """The per-rank fault-injection point; protocol code calls this
        at every phase boundary. Reads PTPU_FAULT_INJECT fresh per call
        (tests flip it between runs). Every call is ALSO a flight-
        recorder beacon point: the phase note (rank, phase, serial) is
        durable before any directive fires, so a SIGKILL here leaves a
        beacon naming exactly the dead rank and phase
        (observability/flight_recorder.py)."""
        from ..observability import flight_recorder as _fr
        _fr.note_phase("barrier", phase, rank=rank, serial=serial)
        plan = world_fault_plan()
        hit = plan["straggle"].get(rank)
        if hit and hit[0] == phase:
            flags.vlog(1, "fault injection: rank %d straggling %.2fs at "
                       "%s", rank, hit[1], phase)
            time.sleep(hit[1])
        hit = plan["drop"].get(rank)
        if hit and hit[0] == phase:
            flags.vlog(0, "fault injection: rank %d dropped at %s",
                       rank, phase)
            _fr.note_phase("barrier", phase, rank=rank, serial=serial,
                           dropped=True)
            raise RankDead(rank, phase)
        hit = plan["crash"].get(rank)
        if hit and hit[0] == phase:
            offset = hit[1]
            if phase == "stage" and offset is not None and staging:
                _truncate_payload_at(staging, int(offset))
            flags.vlog(0, "fault injection: SIGKILL at rank %d phase %s",
                       rank, phase)
            _fr.note_phase("barrier", phase, rank=rank, serial=serial,
                           crashing=True)
            _sigkill_self()  # pragma: no cover

    # -- execution --------------------------------------------------------
    def run(self, fn: Callable[[int], Any],
            timeout: Optional[float] = None) -> List[Any]:
        """Run `fn(rank)` on one thread per live rank; join; return the
        per-rank result list (None for dead/failed ranks). A RankDead
        raised inside fn marks the rank dead and is NOT re-raised (the
        world continues); any other exception is recorded in
        `self.failures` and re-raised from run() after every thread
        joined — a protocol bug must fail the caller, not vanish into a
        thread."""
        from ..observability import flight_recorder as _fr
        from ..observability import tracing as _tracing
        results: List[Any] = [None] * self.world_size
        self.failures = {}

        def _guard(r: int):
            # every span this rank's thread records carries the
            # {world_id, rank, world_size} triple — the per-rank span
            # stream the merged timeline lanes by
            with _tracing.rank_scope(self.world_id, r, self.world_size):
                try:
                    results[r] = fn(r)
                except RankDead as e:
                    self.dead.add(r)
                    _fr.dump_dossier(
                        f"rank {r} dropped at phase {e.phase!r}",
                        rank=r, exc=e)
                except BaseException as e:  # noqa: BLE001 - re-raised below
                    self.failures[r] = e
                    _fr.dump_dossier(f"rank {r} protocol failure",
                                     rank=r, exc=e)

        threads = [threading.Thread(target=_guard, args=(r,),
                                    name=f"world-rank-{r}", daemon=True)
                   for r in self.live_ranks()]
        for t in threads:
            t.start()
        deadline = None if timeout is None else time.monotonic() + timeout
        for t in threads:
            t.join(None if deadline is None
                   else max(0.0, deadline - time.monotonic()))
            enforce(not t.is_alive(),
                    f"ProcessWorld.run: {t.name} did not finish within "
                    f"{timeout}s — protocol deadlock?",
                    exc=InvalidArgumentError)
        if self.failures:
            r = min(self.failures)
            raise self.failures[r]
        return results

    def __repr__(self):
        return (f"ProcessWorld(world_size={self.world_size}, "
                f"chief={self.chief}, dead={sorted(self.dead)})")
