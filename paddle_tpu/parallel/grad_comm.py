"""Communication-optimized data-parallel gradient pipeline.

≙ reference framework/details/fuse_all_reduce_op_pass.cc +
multi_devices_graph_pass.cc:412-453 (the graph pass that decides HOW each
gradient crosses replicas: all-reduce vs reduce-to-owner, fused buckets) —
rebuilt for the explicit per-shard execution mode of ParallelExecutor.

Under the default SPMD mode XLA owns the gradient collectives: the batch is
sharded, parameters are replicated, and the partitioner inserts f32
all-reduces wherever the batch-summed gradient is materialized. That is
correct but leaves two wins on the table the north star cares about
("Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" + EQuARX, PAPERS.md):

  1. reduce-scatter weight update: each shard only needs 1/dp of the
     reduced gradient to run its slice of the optimizer; the full gradient
     never needs to exist anywhere. Wire cost per gradient drops from
     all-reduce(n) to reduce-scatter(n) + all-gather(param-n), and peak
     memory drops the unsharded-gradient residency.
  2. quantized collectives: the gradient's wire format is int8 + block
     scales (or bf16), ~4x fewer bytes, with optional per-replica error
     feedback folding the quantization residual into the next step.

Both need the collective to be OURS, not the partitioner's — so
`comm_optimize_pass` rewrites the program for the explicit pipeline and
ParallelExecutor runs the whole step as per-shard SPMD code (shard_map over
the data axis, other mesh axes left to the partitioner). The pass:

  - splices ONE `dp_grad_comm` op between the vjp_region and every gradient
    consumer (clip / regularizer / optimizer ops read the globally-reduced
    gradient, exactly as before);
  - coalesces small gradients into flat transfer buckets
    (≙ fuse_all_reduce) and gives dp-divisible parameters the sharded
    reduce-scatter path;
  - rewrites sharded-path optimizer ops to run on the local parameter
    slice (`dp_shard_slice` in, `dp_shard_all_gather` out) with their
    same-shaped accumulators marked to live sharded across dp.

The structural contract is asserted by tests/test_comm_structure.py: in
ReduceScatter mode no all-reduce instruction carries gradient bytes, and
the collective byte census matches the analytic formula exactly.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.enforce import InvalidArgumentError, enforce
from ..framework.lowering import grad_var_name
from ..framework.program import Operator, Program
from ..framework.registry import register_op
from .mesh import DATA_AXIS
from .strategy import BuildStrategy, ReduceStrategy

GRAD_COMM_SUFFIX = "@COMM"
SHARD_SUFFIX = "@DP_SHARD"
SHARD_OUT_SUFFIX = "@DP_SHARD_OUT"
ERR_PREFIX = "dp_comm_err"

# Ops whose per-shard semantics differ from the global-batch semantics the
# program was built with: batch_norm folds statistics over the WHOLE batch,
# which per-shard execution would silently turn into per-shard statistics.
_BATCH_GLOBAL_OPS = frozenset({"batch_norm"})

# Loss producers whose per-shard gradient, averaged across equal-size
# shards, equals the global-batch gradient — the identity the whole
# pipeline rests on (grad of global mean == pmean of grads of local
# means). A sum-reduced loss would come out scaled by 1/dp, so anything
# else is REJECTED, not silently rescaled.
_MEAN_LOSS_OPS = frozenset({"mean", "reduce_mean"})

# The executor's shard_map wrapper publishes the current shard's dp index
# here while tracing the step body. Needed because `lax.axis_index` lowers
# to a PartitionId instruction, which XLA rejects inside a PARTIAL-manual
# region (auto tp/sp axes still being SPMD-partitioned make its meaning
# ambiguous); a dp-sharded arange sliced to the local entry is unambiguous
# on every mesh. Trace-time only — tracing is single-threaded per
# executable, and the wrapper clears it on exit.
_CURRENT_DP_INDEX: List = []


class dp_index_scope:
    """Context manager binding the traced dp shard index for op lowerings."""

    def __init__(self, idx):
        self.idx = idx

    def __enter__(self):
        _CURRENT_DP_INDEX.append(self.idx)

    def __exit__(self, *a):
        _CURRENT_DP_INDEX.pop()


def current_dp_index(axis_name: str):
    if _CURRENT_DP_INDEX:
        return _CURRENT_DP_INDEX[-1]
    return jax.lax.axis_index(axis_name)


def explicit_comm_config(strategy: BuildStrategy) -> Optional[Dict]:
    """None when the strategy wants the default SPMD pipeline; otherwise the
    resolved config dict for the explicit per-shard pipeline. The
    PTPU_QUANT_COMM=0 kill switch drops the wire dtype to fp32 but keeps
    the explicit pipeline (the reduce-scatter structure is orthogonal)."""
    from ..core import flags
    enforce((strategy.quant_comm or "") in ("", "int8", "bf16"),
            f"BuildStrategy.quant_comm must be '', 'int8' or 'bf16', got "
            f"{strategy.quant_comm!r}", exc=InvalidArgumentError)
    quant = strategy.quant_comm or ""
    if quant and not flags.get_flag("quant_comm"):
        quant = ""
    explicit = (strategy.reduce_strategy == ReduceStrategy.ReduceScatter
                or bool(strategy.quant_comm))
    if not explicit:
        return None
    return {
        "shard_update": strategy.reduce_strategy == ReduceStrategy.ReduceScatter,
        "quant": quant,
        "block": int(strategy.quant_comm_block),
        "error_feedback": bool(strategy.comm_error_feedback and quant),
        "bucket_bytes": int(strategy.comm_bucket_bytes),
    }


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------

def _grad_pairs(block):
    """[(param var, raw grad name)] from every vjp_region, program order."""
    pairs = []
    for op in block.ops:
        if op.type != "vjp_region":
            continue
        for target in op.attrs["targets"]:
            if not block.has_var(target):
                continue
            v = block.var(target)
            if not getattr(v, "trainable", False):
                continue
            pairs.append((v, grad_var_name(target)))
    return pairs


def _readers(block, name, skip_types=("vjp_region",)):
    return [op for op in block.ops
            if op.type not in skip_types and name in op.input_names()]


def _optimizer_op_for(block, param_name, grad_name):
    """The single optimizer op consuming (param, grad), or None."""
    found = None
    for op in block.ops:
        if op.attrs.get("op_role") != "optimize":
            continue
        if (op.inputs.get("Grad", [None])[0] == grad_name
                and op.inputs.get("Param", [None])[0] == param_name):
            if found is not None:
                return None
            found = op
    return found


def comm_optimize_pass(program: Program, dp: int, config: Dict) -> Program:
    """Clone `program` and rewrite its gradient path for the explicit
    pipeline. Idempotent: a program the pass already produced is returned
    unchanged. The rewrite is recorded as a "dp_comm" span carrying the
    resolved plan config (observability/tracing.py)."""
    if getattr(program, "_dp_comm_applied", False):
        return program
    from ..observability import tracing as _tracing
    with _tracing.span("dp_comm", "grad_comm/comm_optimize_pass", dp=dp,
                       quant=str(config.get("quant", "")),
                       bucket_bytes=int(config.get("bucket_bytes", 0) or 0)):
        return _comm_optimize_pass_impl(program, dp, config)


def _comm_optimize_pass_impl(program: Program, dp: int,
                             config: Dict) -> Program:
    block0 = program.global_block()
    bad = sorted({op.type for op in block0.ops
                  if op.type in _BATCH_GLOBAL_OPS})
    enforce(not bad,
            f"explicit data-parallel gradient pipeline "
            f"(ReduceStrategy.ReduceScatter / BuildStrategy.quant_comm) "
            f"runs the step as per-shard code, but ops {bad} fold "
            f"statistics over the whole batch and would silently compute "
            f"per-shard statistics instead. Use the default AllReduce/"
            f"Reduce strategies for this program",
            exc=InvalidArgumentError)

    for op in block0.ops:
        if op.type != "vjp_region":
            continue
        loss_name = op.attrs["loss"]
        producer = next((o for o in reversed(block0.ops)
                         if loss_name in o.output_names()
                         and o.type != "vjp_region"), None)
        if producer is None or producer.type not in _MEAN_LOSS_OPS:
            # provenance built only on the failing path (index scan +
            # formatting must not run on every successful apply)
            from ..framework.analysis import op_loc
            desc = (op_loc(block0, block0.ops.index(producer), producer)
                    if producer else "<nothing>")
            enforce(False,
                    f"explicit data-parallel gradient pipeline requires a "
                    f"MEAN-reduced loss (got {loss_name!r} produced by "
                    f"{desc}): the per-shard gradients are averaged across "
                    f"shards, which equals the global gradient only for a "
                    f"batch-mean loss. Reduce the loss with layers.mean / "
                    f"reduce_mean, or use the SPMD AllReduce/Reduce "
                    f"strategies",
                    exc=InvalidArgumentError)

    out = program.clone()
    block = out.global_block()
    pairs = _grad_pairs(block)
    if not pairs:
        out._dp_comm_applied = True
        return out

    # tp-rewritten programs (framework/sharding.py tp_shard_pass) execute
    # per-shard at tp-LOCAL shapes: the comm plan — bucket layout, chunk
    # sizes, reshape targets — must be built over those, and the ZeRO-1
    # sharded update slices dim 0 WITHIN each tp shard's local block
    # (optimizer slices sharded over dp per tp shard).
    tp = int(getattr(program, "_tp_size", 0) or 0) \
        if getattr(program, "_tp_applied", False) else 0

    def _tp_local(v):
        from ..framework.sharding import tp_local_shape
        shape = list(v.shape or ())
        if tp > 1 and getattr(v, "tp_spec", None):
            shape = list(tp_local_shape(shape, v.tp_spec, tp))
        return shape

    # --- classify each gradient: sharded reduce-scatter path vs bucket ---
    entries = []       # aligned with the op's X/Out slots
    for param, gname in pairs:
        g = block.var(gname)
        lshape = _tp_local(g)
        numel = int(np.prod(lshape)) if lshape else 1
        opt_op = _optimizer_op_for(block, param.name, gname)
        sole_consumer = (opt_op is not None
                         and len(_readers(block, gname)) == 1)
        spec = getattr(param, "sharding_spec", None)
        # tp-sharded params take the sharded path too once the tp pass has
        # made them executable (the gate already rejected non-tp-sharded
        # annotations); a live annotation WITHOUT the rewrite stays on the
        # bucket path (annotation resolved replicated on this mesh)
        spec_ok = spec is None or tp > 1
        sharded = (config["shard_update"]
                   and sole_consumer
                   and spec_ok
                   and lshape and len(lshape) >= 1
                   and lshape[0] >= dp and lshape[0] % dp == 0
                   # quantized transfers pad every per-destination chunk to
                   # a scale block: a tensor whose chunk is smaller than one
                   # block would pay >= block x dp wire bytes — the bucket
                   # amortizes it with its neighbors instead
                   and (not config["quant"] or numel // dp >= config["block"]))
        entries.append({"grad": gname, "param": param.name,
                        "numel": numel, "shape": lshape,
                        "gshape": list(g.shape or ()),
                        "kind": "sharded" if sharded else "bucket",
                        "opt_op": opt_op if sharded else None})

    if config["shard_update"]:
        n_sharded = sum(1 for e in entries if e["kind"] == "sharded")
        if n_sharded == 0:
            # gradient clip / regularization rewire the optimizer's Grad
            # input to a derived var, which demotes every parameter to the
            # bucket path (full-gradient all-gather, replicated update) —
            # correct, but the ZeRO-1 sharded update never engages. Say so
            # instead of silently degrading (docs/data_parallel.md).
            from ..core import flags
            flags.vlog(0, "ReduceScatter mode: sharded update engaged for "
                       "0/%d parameters (gradient clip/regularization or "
                       "shapes demoted all gradients to the bucket path); "
                       "gradients still travel reduce-scatter+all-gather "
                       "but optimizer state stays replicated",
                       len(entries))

    # --- bucket assembly (≙ fuse_all_reduce): greedy fill by bytes -------
    bucket_cap = max(0, config["bucket_bytes"])
    buckets: List[List[int]] = []
    cur, cur_bytes = [], 0
    for i, e in enumerate(entries):
        if e["kind"] != "bucket":
            continue
        nbytes = e["numel"] * 4
        if cur and (bucket_cap == 0 or cur_bytes + nbytes > bucket_cap):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)

    # --- new vars: comm'd grads, sharded chunks, error-feedback state ----
    for e in entries:
        shape = list(e["shape"])
        if e["kind"] == "sharded":
            shape = [shape[0] // dp] + shape[1:]
        block.create_var(name=e["grad"] + GRAD_COMM_SUFFIX, shape=shape,
                         dtype=block.var(e["grad"]).dtype,
                         stop_gradient=True)

    err_names = []
    if config["error_feedback"]:
        import hashlib
        transfers = ([("sharded", [i]) for i, e in enumerate(entries)
                      if e["kind"] == "sharded"]
                     + [("bucket", b) for b in buckets])
        # namespace the state by the transfer layout (grad names + wire
        # config): two programs — or two configs of one program — sharing
        # a scope must NOT collide on stale residuals of the wrong shape
        # or, worse, silently fold another model's residuals into their
        # gradients. Deterministic across processes (hash of names, no
        # id()s) so a multi-process world agrees on the var names.
        digest = hashlib.sha1(repr(
            ([e["grad"] for e in entries], buckets, config["quant"],
             config["block"], dp, tp)).encode()).hexdigest()[:8]
        for k, (kind, idxs) in enumerate(transfers):
            flat = sum(entries[i]["numel"] for i in idxs)
            if kind == "bucket":
                flat = -(-flat // dp) * dp   # bucket is padded to dp
            # per-replica state: dim 0 IS the data axis (each shard carries
            # only its own residual); ParallelExecutor shards + zero-inits.
            # Under tp every (dp, tp) coordinate quantizes a DIFFERENT
            # local gradient, so dim 0 covers the full dp x tp product
            # (tp_spec makes _state_sharding split it over both axes)
            v = block.create_var(name=f"{ERR_PREFIX}_{digest}_{k}",
                                 shape=[dp * max(tp, 1), flat],
                                 dtype="float32", persistable=True)
            v.stop_gradient = True
            v.dp_replica_state = True
            if tp > 1:
                v.tp_spec = ("tp",) + (None,)
            err_names.append(v.name)

    # --- rewire every consumer of a raw grad to the comm'd grad ----------
    rewire = {e["grad"]: e["grad"] + GRAD_COMM_SUFFIX for e in entries}
    for op in block.ops:
        if op.type == "vjp_region":
            continue
        for slot, names in op.inputs.items():
            op.inputs[slot] = [rewire.get(n, n) for n in names]

    # --- splice the comm op right after the last vjp_region --------------
    # (all vjp_region fwd_ops indices point BEFORE the region op, so any
    # insertion after it keeps the recorded segments valid)
    region_idx = max(i for i, op in enumerate(block.ops)
                     if op.type == "vjp_region")
    comm_op = Operator(
        block, "dp_grad_comm",
        inputs={"X": [e["grad"] for e in entries], "ErrIn": err_names},
        outputs={"Out": [e["grad"] + GRAD_COMM_SUFFIX for e in entries],
                 "ErrOut": err_names},
        attrs={"axis": DATA_AXIS, "dp": dp, "quant": config["quant"],
               "block": config["block"],
               "kinds": [e["kind"] for e in entries],
               "numels": [e["numel"] for e in entries],
               "shapes": [e["shape"] for e in entries],
               "buckets": buckets,
               "error_feedback": config["error_feedback"],
               "op_role": "backward"})
    block.ops.insert(region_idx + 1, comm_op)

    # --- sharded path: optimizer math on the local parameter slice -------
    for e in entries:
        if e["kind"] != "sharded":
            continue
        opt_op = e["opt_op"]
        pname = e["param"]
        pvar = block.var(pname)
        chunk = e["shape"][0] // dp
        block.create_var(name=pname + SHARD_SUFFIX,
                         shape=[chunk] + e["shape"][1:],
                         dtype=pvar.dtype, stop_gradient=True)
        block.create_var(name=pname + SHARD_OUT_SUFFIX,
                         shape=[chunk] + e["shape"][1:],
                         dtype=pvar.dtype, stop_gradient=True)
        # same-shaped accumulators live sharded across dp (ZeRO-1 for real:
        # the executor places them P("dp") so each shard holds 1/dp). The
        # accumulator_of backref (optimizer.py _add_accumulator) declares
        # ownership; the shape check keeps scalar state (beta pows)
        # replicated. Old programs without the backref fall back to the
        # shape heuristic over is_optimizer_state.
        for slot, names in opt_op.inputs.items():
            for n in names:
                if not block.has_var(n):
                    continue
                v = block.var(n)
                owner = getattr(v, "accumulator_of", None)
                if (getattr(v, "is_optimizer_state", False)
                        and (owner == pname or owner is None)
                        and list(v.shape or ()) == e["gshape"]):
                    v.dp_shard_update = True
        opt_op.inputs["Param"] = [pname + SHARD_SUFFIX]
        opt_op.outputs["ParamOut"] = [pname + SHARD_OUT_SUFFIX]
        at = block.ops.index(opt_op)
        block.ops.insert(at, Operator(
            block, "dp_shard_slice", inputs={"X": [pname]},
            outputs={"Out": [pname + SHARD_SUFFIX]},
            attrs={"axis": DATA_AXIS, "chunk": chunk,
                   "op_role": "optimize"}))
        block.ops.insert(at + 2, Operator(
            block, "dp_shard_all_gather",
            inputs={"X": [pname + SHARD_OUT_SUFFIX]},
            outputs={"Out": [pname]},
            attrs={"axis": DATA_AXIS, "op_role": "optimize"}))

    out._bump()
    out._dp_comm_applied = True
    return out


def _compressed_transfer_bytes(n_vals: int, dp: int, quant: str,
                               block: int) -> int:
    """Per-device OUTPUT bytes of one compressed phase (a2a or ag) moving
    `n_vals` f32 values split into dp destination chunks."""
    chunk = n_vals // dp
    cpad = -(-chunk // block) * block
    if quant == "int8":
        per_chunk = cpad + 4 * (cpad // block)     # payload + f32 scales
    elif quant == "bf16":
        per_chunk = 2 * cpad
    else:
        per_chunk = 4 * chunk
    return dp * per_chunk


def analytic_wire_bytes(program: Program, dp: int) -> Optional[Dict]:
    """Per-device interconnect bytes per step of the explicit pipeline, from
    the rewritten program's dp_grad_comm plan — the analytic side of the
    byte balance the HLO census is asserted against
    (tests/test_zero_comm.py). Returns None for non-rewritten programs
    (SPMD mode: use spmd_allreduce_wire_bytes). Ring accounting throughout
    (see probe_common.collective_wire_bytes)."""
    if not getattr(program, "_dp_comm_applied", False):
        return None
    block0 = program.global_block()
    comm = next((op for op in block0.ops if op.type == "dp_grad_comm"), None)
    if comm is None:
        return {"grad_wire_bytes": 0, "param_allgather_wire_bytes": 0,
                "wire_bytes": 0, "grad_f32_bytes": 0, "n_transfers": 0}
    quant = comm.attrs["quant"]
    qblock = comm.attrs["block"]
    kinds, numels = comm.attrs["kinds"], comm.attrs["numels"]
    grad = 0.0
    # launch-count + uncompressed-size side channel for the time model
    # (framework/costs.predicted_step_seconds): how many collective
    # launches the plan issues per step, and the f32 gradient bytes the
    # quantized path must quantize/dequant-sum/requantize
    n_transfers = 0
    grad_f32 = 4 * sum(numels)
    for i, kind in enumerate(kinds):
        if kind != "sharded":
            continue
        n_transfers += 1
        if quant:
            out = _compressed_transfer_bytes(numels[i], dp, quant, qblock)
            grad += out * (dp - 1) / dp            # all_to_all
        else:
            grad += (numels[i] * 4 // dp) * (dp - 1)   # reduce-scatter
    for idxs in comm.attrs["buckets"]:
        flat = sum(numels[i] for i in idxs)
        npad = -(-flat // dp) * dp
        n_transfers += 2                           # reduce + gather phase
        if quant:
            out = _compressed_transfer_bytes(npad, dp, quant, qblock)
            grad += 2 * out * (dp - 1) / dp        # a2a + all_gather
        else:
            grad += (npad * 4 // dp) * (dp - 1)    # reduce-scatter
            grad += (npad * 4) * (dp - 1) / dp     # all_gather
    tp = int(getattr(program, "_tp_size", 0) or 0) \
        if getattr(program, "_tp_applied", False) else 0
    param_ag = 0.0
    for op in block0.ops:
        if op.type != "dp_shard_all_gather":
            continue
        n_transfers += 1
        v = block0.var(op.outputs["Out"][0])
        shape = list(v.shape)
        if tp > 1 and getattr(v, "tp_spec", None):
            from ..framework.sharding import tp_local_shape
            shape = list(tp_local_shape(shape, v.tp_spec, tp))
        n = 1
        for d in shape:
            n *= d
        param_ag += (n * 4) * (dp - 1) / dp
    return {"grad_wire_bytes": int(grad),
            "param_allgather_wire_bytes": int(param_ag),
            "wire_bytes": int(grad + param_ag),
            "grad_f32_bytes": int(grad_f32),
            "n_transfers": int(n_transfers)}


def spmd_zero1_wire_bytes(program: Program, dp: int) -> Dict:
    """Analytic model of the SPMD `ReduceStrategy.Reduce` (ZeRO-1 via
    sharded accumulators) mode: XLA keeps the full gradient all-reduce
    AND all-gathers every parameter whose optimizer state it sharded
    (census-measured on this backend: exactly the allreduce model plus
    the dim0-divisible params' all-gather). APPROXIMATE, unlike the
    explicit-pipeline model: the partitioner owns this lowering, so the
    planner prices it but the ledger never asserts it exact — the
    auto-parallel planner also prefers census-exact modes inside the
    measured noise band for exactly this reason."""
    base = spmd_allreduce_wire_bytes(program, dp)
    ag = 0.0
    n_ag = 0
    for b in program.blocks:
        for v in b.vars.values():
            if not (getattr(v, "trainable", False) and v.persistable):
                continue
            shape = list(v.shape or ())
            if not shape or shape[0] < dp or shape[0] % dp:
                continue
            n = 4
            for d in shape:
                n *= d
            ag += n * (dp - 1) / dp
            n_ag += 1
    return {**base,
            "param_allgather_wire_bytes": int(ag),
            "wire_bytes": int(base["grad_wire_bytes"] + ag),
            "n_transfers": base["n_transfers"] + n_ag,
            "exact": False}


def spmd_allreduce_wire_bytes(program: Program, dp: int) -> Dict:
    """The default SPMD pipeline's analytic equivalent: every trainable
    parameter's gradient rides one f32 all-reduce (ring: 2n(dp-1)/dp)."""
    total = 0
    n_grads = 0
    for b in program.blocks:
        for v in b.vars.values():
            if getattr(v, "trainable", False) and v.persistable:
                n = 1
                for d in v.shape:
                    n *= d
                total += n * 4
                n_grads += 1
    grad = 2.0 * total * (dp - 1) / dp
    return {"grad_wire_bytes": int(grad),
            "param_allgather_wire_bytes": 0,
            "wire_bytes": int(grad),
            "grad_f32_bytes": int(total),
            "n_transfers": int(n_grads)}


# ---------------------------------------------------------------------------
# op lowerings (execute INSIDE the ParallelExecutor's per-shard region,
# where the data axis name is bound)
# ---------------------------------------------------------------------------

@register_op("dp_shard_slice", stop_gradient=True)
def _dp_shard_slice(ctx, ins, attrs):
    p = ins["X"][0]
    i = current_dp_index(attrs["axis"])
    return {"Out": [jax.lax.dynamic_slice_in_dim(
        p, i * attrs["chunk"], attrs["chunk"], axis=0)]}


@register_op("dp_shard_all_gather", stop_gradient=True)
def _dp_shard_all_gather(ctx, ins, attrs):
    return {"Out": [jax.lax.all_gather(ins["X"][0], attrs["axis"], axis=0,
                                       tiled=True)]}


@register_op("dp_grad_comm", stop_gradient=True)
def _dp_grad_comm(ctx, ins, attrs):
    """Cross-replica gradient reduction, explicit form. Each input is this
    shard's gradient of the LOCAL mean loss; each output is the
    corresponding slice (sharded path) or full view (bucket path) of the
    GLOBAL mean gradient — mean over shards == gradient of the global-batch
    mean loss because every shard holds an equal batch slice."""
    from . import collective as C

    axis, dp = attrs["axis"], attrs["dp"]
    quant, block = attrs["quant"], attrs["block"]
    use_ef = attrs["error_feedback"]
    gs = ins["X"]
    errs = list(ins.get("ErrIn", []))
    kinds, numels = attrs["kinds"], attrs["numels"]
    shapes = attrs["shapes"]
    outs: List = [None] * len(gs)
    err_outs: List = []
    ei = 0

    def _take_err():
        nonlocal ei
        e = errs[ei]
        ei += 1
        return e.reshape(-1)   # local slice of the [dp, n] state: [1, n]

    # sharded transfers first, then buckets — the order err state was laid
    # out in by the pass
    for i, kind in enumerate(kinds):
        if kind != "sharded":
            continue
        flat = gs[i].reshape(-1).astype(jnp.float32)
        if use_ef:
            flat = flat + _take_err()
        if quant:
            chunk = C.quantized_reduce_scatter_flat(
                flat, axis, wire_dtype=quant, block=block, mean=True)
            if use_ef:
                err_outs.append(C.quantization_residual_flat(
                    flat, dp, wire_dtype=quant, block=block)
                    .reshape(1, -1))
        else:
            chunk = jax.lax.psum_scatter(flat, axis, scatter_dimension=0,
                                         tiled=True) / dp
        outs[i] = chunk.reshape([shapes[i][0] // dp] + shapes[i][1:])

    for idxs in attrs["buckets"]:
        flat = jnp.concatenate(
            [gs[i].reshape(-1).astype(jnp.float32) for i in idxs])
        n = flat.shape[0]
        npad = -(-n // dp) * dp
        flat = jnp.pad(flat, (0, npad - n))
        if use_ef:
            flat = flat + _take_err()
        if quant:
            full = C.quantized_all_reduce_flat(
                flat, axis, wire_dtype=quant, block=block, mean=True)
            if use_ef:
                err_outs.append(C.quantization_residual_flat(
                    flat, dp, wire_dtype=quant, block=block)
                    .reshape(1, -1))
        else:
            # fp32 without an all-reduce instruction: the same
            # reduce-scatter + all-gather decomposition a ring all-reduce
            # is made of, written out so NO gradient ever rides an
            # all-reduce in ReduceScatter mode (the structural contract)
            part = jax.lax.psum_scatter(flat, axis, scatter_dimension=0,
                                        tiled=True) / dp
            full = jax.lax.all_gather(part, axis, axis=0, tiled=True)
        off = 0
        for i in idxs:
            outs[i] = full[off:off + numels[i]].reshape(
                shapes[i] if shapes[i] else ())
            off += numels[i]

    return {"Out": outs, "ErrOut": err_outs}


# ---------------------------------------------------------------------------
# static-analysis infer specs (framework/analysis.py): these lowerings run
# collectives over the dp mesh axis, so the analyzer cannot abstract-
# evaluate them standalone — the explicit rules state the same shape
# contract the lowerings implement.
# ---------------------------------------------------------------------------

from ..framework.registry import register_infer_spec  # noqa: E402


@register_infer_spec("dp_shard_slice")
def _infer_dp_shard_slice(ictx, in_shapes, in_dtypes, attrs):
    shape = list(in_shapes["X"][0])
    shape[0] = int(attrs["chunk"])
    return {"Out": [(tuple(shape), in_dtypes["X"][0])]}


@register_infer_spec("dp_shard_all_gather")
def _infer_dp_shard_all_gather(ictx, in_shapes, in_dtypes, attrs):
    # the gathered result restores the full parameter — its declared shape
    # (the pass rewires Out to the original param name). With no declared
    # shape the gather factor (dp) is unknowable here: raise rather than
    # validate the un-gathered shard shape as correct (degrades to an
    # infer-error warning in infer_program).
    decl = ictx.declared(ictx.op.outputs["Out"][0]) if ictx else None
    if decl is None:
        raise NotImplementedError(
            "dp_shard_all_gather inference needs the declared Out shape "
            "(output dim0 is shard dim0 * dp, and dp is not an attr)")
    return {"Out": [decl]}


@register_infer_spec("dp_grad_comm")
def _infer_dp_grad_comm(ictx, in_shapes, in_dtypes, attrs):
    dp = max(int(attrs.get("dp", 1)), 1)
    if not (len(attrs["kinds"]) == len(attrs["shapes"])
            == len(in_dtypes["X"])):
        # misaligned plan arrays must not silently truncate via zip — raise
        # so infer_program degrades to an infer-error diagnostic (the
        # attr-schema verifier reports the misalignment at error severity)
        raise ValueError(
            f"dp_grad_comm plan arrays misaligned: kinds="
            f"{len(attrs['kinds'])} shapes={len(attrs['shapes'])} "
            f"X={len(in_dtypes['X'])}")
    outs = []
    for kind, shape, dt in zip(attrs["kinds"], attrs["shapes"],
                               in_dtypes["X"]):
        shape = [int(d) for d in shape]
        if kind == "sharded":
            shape = [shape[0] // dp] + shape[1:]
        outs.append((tuple(shape), np.dtype("float32")))
    errs = [(tuple(s), d) for s, d in zip(in_shapes.get("ErrIn", ()),
                                          in_dtypes.get("ErrIn", ()))]
    return {"Out": outs, "ErrOut": errs}


# ---------------------------------------------------------------------------
# dataflow effect sets (framework/dataflow.py): the dp gradient pipeline's
# axis contract, for the collective-deadlock and replica-divergence
# detectors. dp_grad_comm's per-output consistency (bucket outputs dp-
# consistent, sharded outputs deliberate dp shards) is a custom transfer
# in dataflow.divergence_taints — kinds are per-entry, not per-op.
# ---------------------------------------------------------------------------

from ..framework.registry import register_effects  # noqa: E402


@register_effects("dp_grad_comm")
def _eff_dp_grad_comm(op):
    return {"collective_axes": (op.attrs.get("axis"),)}


@register_effects("dp_shard_slice")
def _eff_dp_shard_slice(op):
    # no wire traffic, but the output is this shard's slice — deliberately
    # dp-varying (the ZeRO-1 local update's input)
    return {"shards_axes": (op.attrs.get("axis"),)}


@register_effects("dp_shard_all_gather")
def _eff_dp_shard_all_gather(op):
    a = op.attrs.get("axis")
    return {"collective_axes": (a,), "resolves_axes": (a,)}
