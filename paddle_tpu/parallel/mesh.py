"""Device mesh model.

≙ reference platform/place.h + platform/nccl_helper.h:81 (NCCLContextMap: the
set of devices and communicators a parallel program runs over). On TPU the
native formulation is a logical N-D mesh over the ICI torus: axes are named
(data / model / pipeline / sequence) and shardings are expressed against axis
names, so the same program scales from 1 chip to a pod by changing the mesh.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core.enforce import InvalidArgumentError, enforce

# Canonical axis names. dp = data, tp = tensor/model, pp = pipeline,
# sp = sequence/context. A mesh may use any subset.
DATA_AXIS = "dp"
MODEL_AXIS = "tp"
PIPELINE_AXIS = "pp"
SEQUENCE_AXIS = "sp"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
              check_rep=None):
    """`jax.shard_map` across jax versions: new jax exports it top-level
    with a `check_vma` flag; jax < 0.5 has it under `jax.experimental`
    with the flag spelled `check_rep`. One adapter so every caller in
    paddle_tpu/parallel works on both."""
    check = check_vma if check_rep is None else check_rep
    try:
        from jax import shard_map as _sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check)
    try:
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=check)
    except TypeError:
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check)


class DeviceMesh:
    """Named logical mesh over physical devices.

    Thin, stable wrapper around `jax.sharding.Mesh` so the rest of the
    framework never touches jax internals directly (the same boundary role
    pybind plays in the reference, paddle/fluid/pybind/pybind.cc:89).
    """

    def __init__(self, devices=None, axes: Optional[Dict[str, int]] = None):
        if devices is None:
            devices = jax.devices()
        devices = list(devices)
        if axes is None:
            axes = {DATA_AXIS: len(devices)}
        shape = tuple(axes.values())
        n = int(np.prod(shape)) if shape else 1
        enforce(n == len(devices),
                f"mesh axes {axes} require {n} devices, got {len(devices)}",
                exc=InvalidArgumentError)
        self.axes = dict(axes)
        self._mesh = Mesh(np.asarray(devices).reshape(shape),
                          tuple(axes.keys()))

    @property
    def jax_mesh(self) -> Mesh:
        return self._mesh

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(self.axes.keys())

    def axis_size(self, name: str) -> int:
        return self.axes.get(name, 1)

    @property
    def num_devices(self) -> int:
        return int(np.prod(list(self.axes.values()))) if self.axes else 1

    # -- sharding constructors -------------------------------------------
    def sharding(self, *spec) -> NamedSharding:
        """NamedSharding from a PartitionSpec-style tuple; axis names not in
        this mesh are dropped (treated as replicated) so model code can
        annotate for the most general mesh."""
        return NamedSharding(self._mesh, self.pspec(*spec))

    def pspec(self, *spec) -> PartitionSpec:
        """PartitionSpec with axis names not in this mesh dropped — lets
        model code annotate for the most general mesh and still run on a
        smaller one."""
        cleaned = []
        for s in spec:
            if s is None:
                cleaned.append(None)
            elif isinstance(s, (tuple, list)):
                kept = tuple(a for a in s if a in self.axes)
                cleaned.append(kept if kept else None)
            else:
                cleaned.append(s if s in self.axes else None)
        return PartitionSpec(*cleaned)

    def replicated(self) -> NamedSharding:
        return NamedSharding(self._mesh, PartitionSpec())

    def batch_sharding(self, ndim: int = None) -> NamedSharding:
        """Shard dim 0 over the data axis (and sp if present for sequence
        dim is NOT assumed here — plain DP batch split, ≙ SplitLoDTensor
        feed splitting, reference parallel_executor.cc:333)."""
        if ndim is None:
            return self.sharding(DATA_AXIS)
        return self.sharding(DATA_AXIS, *([None] * (ndim - 1)))

    def __enter__(self):
        self._ctx = self._mesh.__enter__()
        return self

    def __exit__(self, *a):
        return self._mesh.__exit__(*a)

    def __repr__(self):
        return f"DeviceMesh(axes={self.axes})"


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices=None) -> DeviceMesh:
    return DeviceMesh(devices=devices, axes=axes)


_default_mesh: Optional[DeviceMesh] = None


def get_default_mesh() -> DeviceMesh:
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = DeviceMesh()
    return _default_mesh


def set_default_mesh(mesh: Optional[DeviceMesh]):
    global _default_mesh
    _default_mesh = mesh
