"""Execution/build strategy knobs for the parallel executor.

≙ reference framework/details/execution_strategy.h:83 +
build_strategy.h:23-60. On TPU most of the reference's knobs (thread counts,
op-delay heuristics) are moot — XLA schedules — so the surviving knobs are the
ones that change the compiled program: reduce strategy (allreduce vs sharded
optimizer state, ≙ ReduceStrategy::kAllReduce/kReduce), gradient scale, and
debug dumps.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional


class ReduceStrategy(enum.Enum):
    """≙ BuildStrategy::ReduceStrategy (reference build_strategy.h:44).

    AllReduce: gradients all-reduced, every device runs the full optimizer on
    replicated state (reference multi_devices_graph_pass.cc:419-425).
    Reduce: ZeRO-1 style — optimizer state sharded across the data axis;
    XLA lowers the parameter update to reduce-scatter(grad) + sharded update +
    all-gather(param) (the TPU-native form of the reference's reduce-to-owner
    + broadcast, multi_devices_graph_pass.cc:412-418,445-453).
    ReduceScatter: the explicit comm-optimized pipeline ("Automatic
    Cross-Replica Sharding of Weight Update in Data-Parallel Training",
    PAPERS.md): the step runs as per-shard SPMD code over the data axis,
    every gradient is psum_scatter'd so it is NEVER materialized unsharded,
    optimizer math runs on the local shard only, and the updated shards are
    all-gathered. Structurally asserted: no all-reduce carries gradient
    bytes (tests/test_comm_structure.py). Composes with
    BuildStrategy.quant_comm for quantized transfers.
    """
    AllReduce = 0
    Reduce = 1
    ReduceScatter = 2


class GradientScaleStrategy(enum.Enum):
    """≙ BuildStrategy::GradientScaleStrategy. CoeffNumDevice divides loss
    grad by device count (reference scale_loss_grad_op_handle); under SPMD a
    global `mean` already averages over the full global batch, so One is the
    default and CoeffNumDevice is only for parity with programs that sum."""
    CoeffNumDevice = 0
    One = 1


@dataclass
class BuildStrategy:
    reduce_strategy: ReduceStrategy = ReduceStrategy.AllReduce
    # CoeffNumDevice is rejected at ParallelExecutor construction (the SPMD
    # global-batch mean makes it unnecessary); One is the only implemented
    # mode.
    gradient_scale_strategy: GradientScaleStrategy = GradientScaleStrategy.One
    # RESERVED (accepted, not yet consumed): debug program dumps.
    debug_graphviz_path: str = ""
    # Legacy remat knob (transpiler.memory_optimize); superseded by the
    # static memory planner below — kept accepted for API parity.
    memory_optimize: bool = False
    # --- static memory planner (framework/memory_plan.py) ---------------
    # Apply memory_plan_pass to the program AS RUN (after the tp/dp-comm/
    # pipeline rewrites): liveness-minimizing op scheduling, interference-
    # graph buffer-slot coloring (proven race-free by the r13
    # buffer-reuse detectors on every sanitized apply), and the
    # remat-vs-stash search that segments the backward region under
    # jax.checkpoint when the predicted memory return fits the time
    # budget. Runtime kill switch: PTPU_MEMORY_PLAN=0 (in the executor's
    # compile cache key, so a flip recompiles unplanned).
    memory_plan: bool = False
    # Mandate the remat recompute (jax.checkpoint prevent_cse=True): the
    # searched plan's segments are really recomputed in the backward and
    # the time budget below GATES candidates by their roofline recompute
    # seconds. Default False = CSE-able mode: the recompute is a
    # liveness hint XLA may fold back wherever it would cost wall-clock
    # (measured time-neutral; the budget then only documents the upper
    # bound — no candidate is rejected on time).
    memory_plan_prevent_cse: bool = False
    # The mandated-recompute search's step-time budget: predicted
    # recompute seconds must stay within this fraction of the reference
    # step time (the program's roofline step by default; benches pass
    # the measured step via memory_plan_time_budget_s for CPU-mesh runs
    # where dispatch dominates the roofline).
    memory_plan_time_frac: float = 0.02
    # Optional MEASURED step-time budget in seconds (0 = derive from the
    # roofline via memory_plan_time_frac). On a CPU mesh the roofline
    # underestimates the step by orders of magnitude (dispatch
    # dominates), so a strict roofline budget rejects every remat plan;
    # benches measure the unplanned step once and pass
    # memory_plan_time_frac x measured seconds here.
    memory_plan_time_budget_s: float = 0.0
    enable_sequence_parallel: bool = False
    # --- communication-optimized gradient pipeline (parallel/grad_comm.py) --
    # Wire dtype for gradient collectives: "" = fp32 (off), "int8" =
    # block-scaled symmetric quantization (≙ EQuARX, PAPERS.md), "bf16" =
    # half-width cast. Setting this switches the executor to the explicit
    # per-shard gradient pipeline (like ReduceScatter). Runtime kill switch:
    # PTPU_QUANT_COMM=0 forces fp32 wire regardless of this field.
    quant_comm: str = ""
    # One f32 scale per this many gradient values on the int8 wire.
    quant_comm_block: int = 256
    # Per-replica error feedback: each shard accumulates its quantization
    # residual and adds it to the next step's contribution (state rides the
    # executor's donated carry; see docs/data_parallel.md).
    comm_error_feedback: bool = False
    # Coalesce small gradients into flat transfer buckets of at most this
    # many bytes before the collective (≙ the reference's fuse_all_reduce
    # capability, build_strategy.h fuse_all_reduce_ops_). 0 disables
    # bucketing (one collective per gradient — the probe_overlap A/B side).
    comm_bucket_bytes: int = 4 << 20
    # --- program-level pipeline parallelism (framework/passes.py
    # pipeline_partition_pass + parallel/pipeline.py schedule engine,
    # ≙ the reference's pipeline_trainer section splitting) --------------
    # Number of pipeline stages K. 0/1 = off; K >= 2 cuts the op DAG into K
    # cost-balanced contiguous stages over the mesh's `pp` axis (whose size
    # must equal K). Runtime kill switch: PTPU_PIPELINE=0 runs the program
    # unpartitioned (SPMD, replicated over pp) regardless of this field.
    pipeline_stages: int = 0
    # Microbatches M per step: the global batch must be divisible by
    # dp * M. Bubble fraction is (K-1)/(M+K-1) for both schedules — raise M
    # to amortize the fill/drain bubble.
    num_microbatches: int = 1
    # 'gpipe' (all forwards, then all backwards — activation stash grows
    # with M) or '1f1b' (warmup / 1-forward-1-backward steady state /
    # drain — stash bounded at <= K in-flight microbatches; the default).
    pipeline_schedule: str = "1f1b"
    # --- host-offload tier (framework/offload.py) ------------------------
    # ZeRO-offload optimizer state: the Reduce/ReduceScatter accumulator
    # shards live in the pinned host pool between steps and round-trip
    # per step on the shared transfer stream (restore before the step,
    # spill after), overlapped behind forward/backward compute. HBM held
    # by optimizer state drops to ~one in-flight bucket; costs.predict's
    # `offload` section prices the PCIe round-trip against the overlap
    # window so the planner can refuse it when the transfer cannot hide.
    # Runtime kill switch: PTPU_OFFLOAD=0 keeps state device-resident
    # regardless of this field.
    offload_optimizer_state: bool = False
    # Let the memory planner's remat-vs-stash search also consider
    # stashing checkpointed activations to the host tier (third
    # candidate class beside recompute and device stash), priced on the
    # same PCIe roofline. On the CPU mesh the stash executes in
    # ADVISORY mode (decision recorded + priced, transfer not lowered —
    # same discipline as the planner's pp stage decisions); the TPU
    # lowering is ROADMAP item 5(a).
    memory_plan_stash_to_host: bool = False
    # --- auto-parallel planner (framework/auto_parallel.py) --------------
    # Let the framework CHOOSE the parallelism: on first prepare the
    # executor runs the cost-model-guided search over the dp x pp x tp
    # strategy space (mesh factorization, reduce mode, pipeline
    # schedule/microbatches, comm buckets, memory plan) and adopts the
    # chosen knobs + mesh. The fields above then serve as the BASE the
    # planner overwrites; knobs that change training numerics
    # (quant_comm, comm_error_feedback) are never flipped implicitly —
    # they stay exactly as set here (auto_parallel.
    # numerics_preserving_space). On elastic restore to a CHANGED world
    # size the planner re-plans and adopts the re-plan only when its
    # predicted step time beats keeping the restored strategy
    # (parallel/elastic.py restore_train_state). Runtime kill switch:
    # PTPU_AUTO_PARALLEL=0 (in the executor's compile cache key) runs
    # the strategy/mesh exactly as constructed.
    auto_parallel: bool = False


@dataclass
class ExecutionStrategy:
    # ≙ num_iteration_per_drop_scope (scope_buffered_ssa_graph_executor.h:37):
    # how many steps between host syncs/scope cleanups. Under jit this only
    # controls how often we block_until_ready for error surfacing.
    num_iteration_per_drop_scope: int = 100
    use_experimental_executor: bool = False
    num_threads: int = 0               # accepted for API parity; XLA schedules
