"""Execution/build strategy knobs for the parallel executor.

≙ reference framework/details/execution_strategy.h:83 +
build_strategy.h:23-60. On TPU most of the reference's knobs (thread counts,
op-delay heuristics) are moot — XLA schedules — so the surviving knobs are the
ones that change the compiled program: reduce strategy (allreduce vs sharded
optimizer state, ≙ ReduceStrategy::kAllReduce/kReduce), gradient scale, and
debug dumps.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional


class ReduceStrategy(enum.Enum):
    """≙ BuildStrategy::ReduceStrategy (reference build_strategy.h:44).

    AllReduce: gradients all-reduced, every device runs the full optimizer on
    replicated state (reference multi_devices_graph_pass.cc:419-425).
    Reduce: ZeRO-1 style — optimizer state sharded across the data axis;
    XLA lowers the parameter update to reduce-scatter(grad) + sharded update +
    all-gather(param) (the TPU-native form of the reference's reduce-to-owner
    + broadcast, multi_devices_graph_pass.cc:412-418,445-453).
    """
    AllReduce = 0
    Reduce = 1


class GradientScaleStrategy(enum.Enum):
    """≙ BuildStrategy::GradientScaleStrategy. CoeffNumDevice divides loss
    grad by device count (reference scale_loss_grad_op_handle); under SPMD a
    global `mean` already averages over the full global batch, so One is the
    default and CoeffNumDevice is only for parity with programs that sum."""
    CoeffNumDevice = 0
    One = 1


@dataclass
class BuildStrategy:
    reduce_strategy: ReduceStrategy = ReduceStrategy.AllReduce
    # CoeffNumDevice is rejected at ParallelExecutor construction (the SPMD
    # global-batch mean makes it unnecessary); One is the only implemented
    # mode.
    gradient_scale_strategy: GradientScaleStrategy = GradientScaleStrategy.One
    # RESERVED (accepted, not yet consumed): debug program dumps and
    # remat-based memory optimization land with the observability layer.
    debug_graphviz_path: str = ""
    memory_optimize: bool = False
    enable_sequence_parallel: bool = False


@dataclass
class ExecutionStrategy:
    # ≙ num_iteration_per_drop_scope (scope_buffered_ssa_graph_executor.h:37):
    # how many steps between host syncs/scope cleanups. Under jit this only
    # controls how often we block_until_ready for error surfacing.
    num_iteration_per_drop_scope: int = 100
    use_experimental_executor: bool = False
    num_threads: int = 0               # accepted for API parity; XLA schedules
