"""Parallelism: SPMD execution over a device mesh.

Capability equivalent of the reference's multi-device stack — ParallelExecutor
+ MultiDevSSAGraphBuilder + NCCL op handles (reference
paddle/fluid/framework/parallel_executor.cc:119,
framework/details/multi_devices_graph_pass.cc:320,
details/all_reduce_op_handle.cc) — re-designed TPU-first: instead of
replicating the program per device and inserting collective *ops*, the whole
training step is compiled once under `jax.jit` with `jax.sharding`
annotations over a `Mesh`; XLA partitions the computation and inserts ICI
collectives (all-reduce / reduce-scatter / all-gather) itself.
"""

from .mesh import (DeviceMesh, get_default_mesh, set_default_mesh,  # noqa: F401
                   make_mesh)
from .strategy import BuildStrategy, ExecutionStrategy, ReduceStrategy  # noqa: F401
from .parallel_executor import ParallelExecutor  # noqa: F401
from . import collective  # noqa: F401
from . import grad_comm  # noqa: F401
from . import tensor_parallel  # noqa: F401
from . import pipeline  # noqa: F401
from . import ring_attention  # noqa: F401
from . import sharded_embedding  # noqa: F401
from . import auto_shard  # noqa: F401
from .auto_shard import annotate_tp  # noqa: F401
from . import elastic  # noqa: F401
from .elastic import (latest_snapshot, restore_train_state,  # noqa: F401
                      save_train_state)
from . import process_world  # noqa: F401
from .process_world import ProcessWorld  # noqa: F401
from . import reshard  # noqa: F401
