"""Pipeline parallelism: GPipe-style microbatched stage pipeline over the
`pp` mesh axis.

NEW capability with no reference analogue (SURVEY.md §2.3: the reference has
no pipeline schedule). Design: stage parameters are stacked with a leading
[num_stages] dim sharded over `pp`; inside `shard_map` each device holds one
stage and the schedule is a scan over num_microbatches + num_stages - 1
ticks, rotating activations along the ring with `ppermute`. Differentiable:
reverse-mode AD re-runs the ring backwards, which is exactly the 1F1B-ish
backward wave.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax.tree_util import tree_map

from .collective import ring_perm
from .mesh import PIPELINE_AXIS, DeviceMesh, shard_map


def _pipeline_body(stage_fn: Callable, axis_name: str):
    """Returns the per-device pipeline function: (stage_params, x) -> y where
    stage_params is this device's stage (leading stacked dim already split
    away by shard_map), x: [M, mb, ...] microbatched input (replicated)."""

    def body(params, x):
        params = tree_map(lambda p: p[0], params)  # drop the stage dim slice
        n = jax.lax.psum(1, axis_name)
        idx = jax.lax.axis_index(axis_name)
        m = x.shape[0]
        ticks = m + n - 1
        perm = ring_perm(n)

        state = jnp.zeros(x.shape[1:], x.dtype)       # in-flight activation
        y = jnp.zeros(x.shape, x.dtype)               # outputs (last stage)
        # the scan carry is device-varying (each stage holds different
        # activations) — mark the initial zeros as such for shard_map's
        # varying-axis type system (jax < 0.6 has no pvary and no vma
        # tracking either, so nothing needs marking there)
        if hasattr(jax.lax, "pvary"):
            state = jax.lax.pvary(state, (axis_name,))
            y = jax.lax.pvary(y, (axis_name,))

        def tick(carry, t):
            state, y = carry
            # stage 0 ingests microbatch t (if any); others take the ring
            feed = x[jnp.clip(t, 0, m - 1)]
            inp = jnp.where(idx == 0, feed, state)
            out = stage_fn(params, inp)
            # last stage emits microbatch t-(n-1)
            ot = jnp.clip(t - (n - 1), 0, m - 1)
            emit = (idx == n - 1) & (t >= n - 1)
            y = jnp.where(emit, y.at[ot].set(out), y)
            state = jax.lax.ppermute(out, axis_name, perm)
            return (state, y), None

        (state, y), _ = jax.lax.scan(tick, (state, y), jnp.arange(ticks))
        # only the last device holds real outputs; share them over the ring
        y = jax.lax.psum(jnp.where(idx == n - 1, y, jnp.zeros_like(y)),
                         axis_name)
        return y

    return body


def pipeline_apply(mesh: DeviceMesh, stage_fn: Callable, stacked_params, x,
                   num_microbatches: int, axis_name: str = PIPELINE_AXIS):
    """Run `stage_fn(params_i, x) -> y` as a pipeline over the pp axis.

    stacked_params: pytree whose leaves have leading dim == pp axis size.
    x: [B, ...] global batch; it is reshaped to [M, B/M, ...] microbatches.
    Returns y: [B, ...] (same trailing shape as stage output).
    """
    n = mesh.axis_size(axis_name)
    b = x.shape[0]
    assert b % num_microbatches == 0, (
        f"batch {b} not divisible by num_microbatches {num_microbatches}")
    xm = x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])

    # The ring buffer requires stage output shape/dtype == input (activation
    # flows through identical stages). Fail fast with a clear message.
    import jax as _jax
    from jax.tree_util import tree_map as _tm
    probe_params = _tm(lambda p: jax.ShapeDtypeStruct(p.shape[1:], p.dtype),
                      stacked_params)
    probe_x = jax.ShapeDtypeStruct(xm.shape[1:], xm.dtype)
    out_shape = _jax.eval_shape(lambda p, h: stage_fn(p, h), probe_params,
                                probe_x)
    if (out_shape.shape, out_shape.dtype) != (probe_x.shape, probe_x.dtype):
        raise ValueError(
            f"pipeline stage must map activations to the same shape/dtype "
            f"(got {probe_x.shape}/{probe_x.dtype} -> "
            f"{out_shape.shape}/{out_shape.dtype}); wrap shape-changing "
            f"layers into the first/last stage outside the pipeline")

    param_specs = tree_map(
        lambda p: P(*([axis_name] + [None] * (p.ndim - 1))), stacked_params)
    body = _pipeline_body(stage_fn, axis_name)
    f = shard_map(body, mesh=mesh.jax_mesh,
                  in_specs=(param_specs, P()), out_specs=P(),
                  )
    ym = f(stacked_params, xm)
    return ym.reshape((b,) + ym.shape[2:])
