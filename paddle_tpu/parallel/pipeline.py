"""Pipeline parallelism over the `pp` mesh axis.

Two layers live here:

1. `pipeline_apply` — the original GPipe-style ring for UNIFORM stages
   (stage parameters stacked with a leading [num_stages] dim sharded over
   `pp`; reverse-mode AD re-runs the ring backwards). Kept for callers that
   hand-stack per-stage params.

2. The program-level executor mode (≙ the reference's `pipeline_trainer` /
   program section splitting): `framework/passes.py:pipeline_partition_pass`
   cuts the op DAG into K contiguous stages and splices explicit
   `pp_send`/`pp_recv` ops at the cuts; the `pp_pipeline_region` engine in
   this module then runs a STATIC tick schedule — GPipe or non-interleaved
   1F1B (warmup / 1-forward-1-backward steady state / drain) — as one
   `lax.scan`, moving boundary activations and boundary gradients with one
   `ppermute` each per tick (GDP frames the placement as cost-modeled graph
   partitioning, arXiv 1910.01578; keeping stage transfers as explicit,
   census-able collectives follows arXiv 2112.01075 — the same discipline as
   the r08 dp_grad_comm pipeline). The backward per microbatch recomputes
   the stage forward from a stashed boundary input (activation
   checkpointing at stage granularity) and accumulates parameter gradients
   across microbatches; 1F1B's whole point is the bounded stash
   (≤ num_stages in-flight microbatches vs GPipe's num_microbatches).
   The schedule is a host-side table (`build_schedule`), so the measured
   bubble census (`schedule_census`, tools/probe_bubble.py) reads the SAME
   tables the device executes.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax.tree_util import tree_map

from ..core.enforce import InvalidArgumentError, enforce
from .collective import ring_perm
from .mesh import PIPELINE_AXIS, DeviceMesh, shard_map


def _pipeline_body(stage_fn: Callable, axis_name: str):
    """Returns the per-device pipeline function: (stage_params, x) -> y where
    stage_params is this device's stage (leading stacked dim already split
    away by shard_map), x: [M, mb, ...] microbatched input (replicated)."""

    def body(params, x):
        params = tree_map(lambda p: p[0], params)  # drop the stage dim slice
        n = jax.lax.psum(1, axis_name)
        idx = jax.lax.axis_index(axis_name)
        m = x.shape[0]
        ticks = m + n - 1
        perm = ring_perm(n)

        state = jnp.zeros(x.shape[1:], x.dtype)       # in-flight activation
        y = jnp.zeros(x.shape, x.dtype)               # outputs (last stage)
        # the scan carry is device-varying (each stage holds different
        # activations) — mark the initial zeros as such for shard_map's
        # varying-axis type system (jax < 0.6 has no pvary and no vma
        # tracking either, so nothing needs marking there)
        if hasattr(jax.lax, "pvary"):
            state = jax.lax.pvary(state, (axis_name,))
            y = jax.lax.pvary(y, (axis_name,))

        def tick(carry, t):
            state, y = carry
            # stage 0 ingests microbatch t (if any); others take the ring
            feed = x[jnp.clip(t, 0, m - 1)]
            inp = jnp.where(idx == 0, feed, state)
            out = stage_fn(params, inp)
            # last stage emits microbatch t-(n-1)
            ot = jnp.clip(t - (n - 1), 0, m - 1)
            emit = (idx == n - 1) & (t >= n - 1)
            y = jnp.where(emit, y.at[ot].set(out), y)
            state = jax.lax.ppermute(out, axis_name, perm)
            return (state, y), None

        (state, y), _ = jax.lax.scan(tick, (state, y), jnp.arange(ticks))
        # only the last device holds real outputs; share them over the ring
        y = jax.lax.psum(jnp.where(idx == n - 1, y, jnp.zeros_like(y)),
                         axis_name)
        return y

    return body


def pipeline_apply(mesh: DeviceMesh, stage_fn: Callable, stacked_params, x,
                   num_microbatches: int, axis_name: str = PIPELINE_AXIS):
    """Run `stage_fn(params_i, x) -> y` as a pipeline over the pp axis.

    stacked_params: pytree whose leaves have leading dim == pp axis size.
    x: [B, ...] global batch; it is reshaped to [M, B/M, ...] microbatches.
    Returns y: [B, ...] (same trailing shape as stage output).
    """
    n = mesh.axis_size(axis_name)
    b = x.shape[0]
    enforce(num_microbatches >= 1,
            f"num_microbatches must be >= 1, got {num_microbatches}",
            exc=InvalidArgumentError)
    enforce(b % num_microbatches == 0,
            f"pipeline_apply: batch size {b} is not divisible by "
            f"num_microbatches {num_microbatches}; every microbatch must be "
            f"equal-sized (the schedule averages per-microbatch losses and "
            f"an uneven tail would be silently re-weighted). Pad the batch "
            f"or pick a divisor of {b}",
            exc=InvalidArgumentError)
    xm = x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])

    # The ring buffer requires stage output shape/dtype == input (activation
    # flows through identical stages). Fail fast with a clear message.
    import jax as _jax
    from jax.tree_util import tree_map as _tm
    probe_params = _tm(lambda p: jax.ShapeDtypeStruct(p.shape[1:], p.dtype),
                      stacked_params)
    probe_x = jax.ShapeDtypeStruct(xm.shape[1:], xm.dtype)
    out_shape = _jax.eval_shape(lambda p, h: stage_fn(p, h), probe_params,
                                probe_x)
    if (out_shape.shape, out_shape.dtype) != (probe_x.shape, probe_x.dtype):
        raise ValueError(
            f"pipeline stage must map activations to the same shape/dtype "
            f"(got {probe_x.shape}/{probe_x.dtype} -> "
            f"{out_shape.shape}/{out_shape.dtype}); wrap shape-changing "
            f"layers into the first/last stage outside the pipeline")

    param_specs = tree_map(
        lambda p: P(*([axis_name] + [None] * (p.ndim - 1))), stacked_params)
    body = _pipeline_body(stage_fn, axis_name)
    f = shard_map(body, mesh=mesh.jax_mesh,
                  in_specs=(param_specs, P()), out_specs=P(),
                  )
    ym = f(stacked_params, xm)
    return ym.reshape((b,) + ym.shape[2:])


# ===========================================================================
# program-level pipeline execution (pp_pipeline_region)
# ===========================================================================

PP_REGION_TYPE = "pp_pipeline_region"
PIPELINE_SCHEDULES = ("gpipe", "1f1b")

# The executor's shard_map wrapper publishes the traced pp stage index here
# (same mechanism and rationale as grad_comm._CURRENT_DP_INDEX: inside the
# full-manual region a dp/pp-sharded arange sliced to the local entry is the
# index form every jax/XLA version accepts).
_CURRENT_PP_INDEX: List = []


class pp_index_scope:
    """Context manager binding the traced pp stage index for the region."""

    def __init__(self, idx):
        self.idx = idx

    def __enter__(self):
        _CURRENT_PP_INDEX.append(self.idx)

    def __exit__(self, *a):
        _CURRENT_PP_INDEX.pop()


def current_pp_index(axis_name: str):
    if _CURRENT_PP_INDEX:
        return _CURRENT_PP_INDEX[-1]
    return jax.lax.axis_index(axis_name)


def pipeline_config(strategy) -> Optional[Dict]:
    """None when the strategy does not ask for program-level pipelining (or
    the PTPU_PIPELINE=0 kill switch is down); otherwise the resolved config.
    Resolved at prepare time so a runtime kill-switch flip recompiles (the
    flag rides the executor's compile cache key)."""
    from ..core import flags
    stages = int(getattr(strategy, "pipeline_stages", 0) or 0)
    if stages <= 1 or not flags.get_flag("pipeline"):
        return None
    sched = getattr(strategy, "pipeline_schedule", "1f1b")
    enforce(sched in PIPELINE_SCHEDULES,
            f"BuildStrategy.pipeline_schedule must be one of "
            f"{PIPELINE_SCHEDULES}, got {sched!r}",
            exc=InvalidArgumentError)
    m = int(getattr(strategy, "num_microbatches", 1) or 1)
    enforce(m >= 1,
            f"BuildStrategy.num_microbatches must be >= 1, got {m}",
            exc=InvalidArgumentError)
    return {"stages": stages, "microbatches": m, "schedule": sched}


# ---------------------------------------------------------------------------
# schedule tables: host-side slot-synchronous simulation
# ---------------------------------------------------------------------------

class PipelineSchedule:
    """Static tick tables driving the region scan. Slot model: each tick a
    stage performs ONE forward or ONE backward (or idles — a bubble);
    boundary activations/gradients shifted at END of tick arrive for the
    next tick. Tables are [ticks, num_stages] int arrays of microbatch
    indices, -1 = none."""

    def __init__(self, name, num_microbatches, num_stages, fwd_mb, bwd_mb,
                 fwd_slot, bwd_slot):
        self.name = name
        self.num_microbatches = num_microbatches
        self.num_stages = num_stages
        self.fwd_mb = fwd_mb                      # [T, K]
        self.bwd_mb = bwd_mb                      # [T, K]
        self.ticks = fwd_mb.shape[0]
        self._fwd_slot = fwd_slot                 # [K][M] completion slots
        self._bwd_slot = bwd_slot
        K, T = num_stages, self.ticks
        # arrival tables: what lands on stage k's stash at END of tick t
        self.arr_act = np.full((T, K), -1, np.int32)
        self.arr_act[:, 1:] = fwd_mb[:, :-1]
        self.arr_grad = np.full((T, K), -1, np.int32)
        self.arr_grad[:, :-1] = bwd_mb[:, 1:]
        # stash depths: peak count of microbatches live (arrived, backward
        # not yet done) — the live window is contiguous in mb index (fwd and
        # bwd both issue in order), so `mb % depth` is collision-free
        self.act_stash_depth = max(1, max(
            self._peak_live(k, arrival="act") for k in range(K)))
        self.grad_stash_depth = max(1, max(
            self._peak_live(k, arrival="grad") for k in range(K)))

    def _peak_live(self, k, arrival):
        """Peak occupancy of stage k's stash: live interval of microbatch m
        is (arrival_slot, bwd_slot] — arrival is the upstream fwd (act) or
        downstream bwd (grad); edge stages (0 for act, K-1 for grad) own
        the value locally (no stash needed), counted from local issue."""
        M, K = self.num_microbatches, self.num_stages
        if arrival == "act":
            arr = (self._fwd_slot[k - 1] if k > 0 else self._fwd_slot[k])
        else:
            if k == K - 1:
                return 0
            arr = self._bwd_slot[k + 1]
        done = self._bwd_slot[k]
        peak = 0
        for t in range(self.ticks + 1):
            live = sum(1 for m in range(M) if arr[m] < t <= done[m])
            peak = max(peak, live)
        return peak

    def stash_census(self):
        """Per-stage peak stashed-microbatch count (activation liveness):
        for stage k, the max number of microbatches whose forward input is
        held for a pending backward. This is DERIVED from the executed
        tables, not assumed — tools/probe_bubble.py and the tests read it."""
        M, K = self.num_microbatches, self.num_stages
        return [self._peak_live(k, "act") for k in range(K)]

    def bubble_census(self):
        M, K, T = self.num_microbatches, self.num_stages, self.ticks
        idle = [int(T - (self.fwd_mb[:, k] >= 0).sum()
                    - (self.bwd_mb[:, k] >= 0).sum()) for k in range(K)]
        return {
            "ticks": T,
            "work_slots_per_stage": 2 * M,
            "idle_slots_per_stage": idle,
            "bubble_fraction_per_stage": [i / T for i in idle],
            "bubble_fraction": (T - 2 * M) / T,
            "analytic_bubble_fraction": (K - 1) / (M + K - 1),
        }


def build_schedule(name: str, num_microbatches: int,
                   num_stages: int) -> PipelineSchedule:
    """Simulate the slot-synchronous schedule and emit its tick tables.

    One simulator, one knob: the per-stage in-flight limit. GPipe allows M
    microbatches in flight (all forwards first, flush at the end); 1F1B
    caps stage k at min(K - k, M) — after its warmup a stage must retire a
    backward before admitting the next forward, which is exactly the
    1-forward-1-backward steady state and the bounded activation stash.

    Recorded as a "pp_tick" span (schedule/M/K provenance): the tick
    tables are THE pipeline control artifact, so their construction cost
    and config land in the trace next to the compile they feed."""
    from ..observability import tracing as _tracing
    with _tracing.span("pp_tick", "pipeline/build_schedule",
                       schedule=str(name), microbatches=int(num_microbatches),
                       stages=int(num_stages)):
        return _build_schedule_impl(name, num_microbatches, num_stages)


def _build_schedule_impl(name: str, num_microbatches: int,
                         num_stages: int) -> PipelineSchedule:
    M, K = int(num_microbatches), int(num_stages)
    enforce(name in PIPELINE_SCHEDULES,
            f"unknown pipeline schedule {name!r}; known: "
            f"{PIPELINE_SCHEDULES}", exc=InvalidArgumentError)
    enforce(M >= 1 and K >= 1, f"need M >= 1, K >= 1 (got M={M}, K={K})",
            exc=InvalidArgumentError)
    limit = [M] * K if name == "gpipe" else [min(K - k, M) for k in range(K)]
    fwd_slot = [[None] * M for _ in range(K)]
    bwd_slot = [[None] * M for _ in range(K)]
    next_f, next_b = [0] * K, [0] * K
    rows_f, rows_b = [], []
    cap = 4 * (M + K) + 8
    t = 0
    while any(nb < M for nb in next_b):
        enforce(t < cap, f"pipeline schedule simulation did not converge "
                f"(schedule={name}, M={M}, K={K}) — scheduler bug",
                exc=InvalidArgumentError)
        row_f, row_b = [-1] * K, [-1] * K
        for k in range(K):
            nf, nb = next_f[k], next_b[k]
            f_avail = nf < M and (
                k == 0 or (fwd_slot[k - 1][nf] is not None
                           and fwd_slot[k - 1][nf] < t))
            b_avail = (nb < M and nb < nf and fwd_slot[k][nb] < t
                       and (k == K - 1 or (bwd_slot[k + 1][nb] is not None
                                           and bwd_slot[k + 1][nb] < t)))
            in_flight = nf - nb
            if b_avail and (in_flight >= limit[k] or nf >= M
                            or not f_avail):
                row_b[k] = nb
                bwd_slot[k][nb] = t
                next_b[k] += 1
            elif f_avail and in_flight < limit[k]:
                row_f[k] = nf
                fwd_slot[k][nf] = t
                next_f[k] += 1
        rows_f.append(row_f)
        rows_b.append(row_b)
        t += 1
    return PipelineSchedule(name, M, K,
                            np.asarray(rows_f, np.int32),
                            np.asarray(rows_b, np.int32),
                            fwd_slot, bwd_slot)


def schedule_census(name: str, num_microbatches: int,
                    num_stages: int) -> Dict:
    """The bubble + activation-liveness census of one schedule, from the
    same tables the region executes. `bubble_fraction` counts a stage's
    idle slots out of total ticks; for both schedules it lands exactly on
    the analytic (K-1)/(M+K-1)."""
    s = build_schedule(name, num_microbatches, num_stages)
    out = {"schedule": name, "num_microbatches": s.num_microbatches,
           "num_stages": s.num_stages}
    out.update(s.bubble_census())
    stash = s.stash_census()
    out["peak_stash_per_stage"] = stash
    out["peak_stash"] = max(stash)
    out["act_stash_depth"] = s.act_stash_depth
    out["grad_stash_depth"] = s.grad_stash_depth
    return out


# ---------------------------------------------------------------------------
# op stubs: constructed by pipeline_partition_pass, executed by the engine
# ---------------------------------------------------------------------------

from ..framework.registry import LowerCtx, register_op  # noqa: E402


@register_op("pp_send", stop_gradient=True)
def _pp_send_stub(ctx, ins, attrs):
    raise RuntimeError(
        "pp_send marks a pipeline stage boundary; it is executed by the "
        "pp_pipeline_region scheduler, never lowered directly")


@register_op("pp_recv", stop_gradient=True)
def _pp_recv_stub(ctx, ins, attrs):
    raise RuntimeError(
        "pp_recv marks a pipeline stage boundary; it is executed by the "
        "pp_pipeline_region scheduler, never lowered directly")


@register_op(PP_REGION_TYPE, stop_gradient=True)
def _pp_region_stub(ctx, ins, attrs):
    raise RuntimeError(
        "pp_pipeline_region must be executed via the block planner "
        "(framework/lowering.py REGION_RUNNERS)")


# static-analysis infer specs (framework/analysis.py): the boundary ops are
# executed by the region scheduler, never lowered, so the analyzer needs
# their shape contract stated explicitly. pp_pipeline_region itself is
# engine-interpreted (Grads mirror the diff targets), like vjp_region.

from ..framework.registry import register_infer_spec  # noqa: E402


@register_infer_spec("pp_send")
def _infer_pp_send(ictx, in_shapes, in_dtypes, attrs):
    # Out is a zero-size token tying the cut into the DAG; the real
    # transfer is the scheduler's packed f32 buffer
    import numpy as _np
    return {"Out": [((0,), _np.dtype("float32"))]}


@register_infer_spec("pp_recv")
def _infer_pp_recv(ictx, in_shapes, in_dtypes, attrs):
    # re-binds the crossing activations on the consuming stage: shapes are
    # exactly the declared shapes of the names it re-binds
    outs = []
    for name in ictx.op.outputs["Out"]:
        decl = ictx.declared(name)
        if decl is None:
            raise NotImplementedError(
                f"pp_recv output {name!r} has no declared shape")
        outs.append(decl)
    return {"Out": outs}


# dataflow effect sets (framework/dataflow.py): the boundary ops move a
# value between pp shards (one ppermute each per tick) — a transfer, not a
# reduction, so they neither resolve nor shard any axis's consistency; the
# region op runs the whole schedule's collectives over pp (plus the dp
# grad pmean when it owns the dp reduction, i.e. reduce_dp).

from ..framework.registry import register_effects  # noqa: E402


@register_effects("pp_send")
def _eff_pp_send(op):
    return {"collective_axes": (PIPELINE_AXIS,)}


@register_effects("pp_recv")
def _eff_pp_recv(op):
    return {"collective_axes": (PIPELINE_AXIS,)}


@register_effects(PP_REGION_TYPE)
def _eff_pp_region(op):
    axes = [op.attrs.get("axis") or PIPELINE_AXIS]
    if op.attrs.get("reduce_dp") and op.attrs.get("dp_axis"):
        axes.append(op.attrs["dp_axis"])
    return {"collective_axes": tuple(axes)}


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

def _resolve_cuts(block, stage_ops):
    """[(cut names tuple)] for cuts 0..K-2, read off the spliced pp_send
    ops — the program IS the source of truth for what crosses each
    boundary."""
    from ..framework.analysis import op_loc
    cuts = []
    for k, ops in enumerate(stage_ops[:-1]):
        send = [op for op in ops if op.type == "pp_send"]
        if len(send) != 1:
            desc = (op_loc(block, block.ops.index(ops[0]), ops[0])
                    if ops else "<empty stage>")
            enforce(False,
                    f"stage {k} ({desc} ...) must end in exactly one "
                    f"pp_send, found {len(send)} — program not produced by "
                    f"pipeline_partition_pass?", exc=InvalidArgumentError)
        cuts.append(tuple(send[0].inputs["X"]))
    return cuts


def run_pp_region(region_op, seg_indices, env, block, ctx):
    """Execute a pp_pipeline_region: the microbatched 1F1B/GPipe schedule
    over the pp mesh axis, inside the executor's full-manual shard_map.

    Publishes into `env`: the loss (mean over all microbatches, LOCAL to
    the dp shard), loss@GRAD (ones), and every target's @GRAD — the
    gradient of the microbatch-mean loss, summed over pipeline stages
    (psum over pp) and, when `reduce_dp`, averaged over the dp axis.
    Forward activations are deliberately NOT published: they only ever
    exist per-microbatch on their stage's device."""
    from ..framework.lowering import grad_var_name, run_op

    attrs = region_op.attrs
    K = int(attrs["num_stages"])
    M = int(attrs["num_microbatches"])
    axis = attrs["axis"]
    dp_axis = attrs.get("dp_axis") or None
    target_names: List[str] = list(attrs["targets"])
    loss_name: str = attrs["loss"]
    batch_led = set(attrs["batch_led"])
    stage_ops = [[block.ops[i] for i in idxs] for idxs in attrs["stages"]]
    cut_names = _resolve_cuts(block, stage_ops)
    pp_idx = current_pp_index(axis)
    f32 = jnp.float32

    missing = [n for n in target_names if n not in env]
    if missing:
        from ..core.enforce import NotFoundError
        raise NotFoundError(
            f"pp_pipeline_region differentiates wrt {missing} which are "
            f"not initialized — run the startup program or feed them")
    params = tuple(env[n] for n in target_names)

    # -- classify external inputs: microbatched vs replicated-static ------
    ext_names = [n for n in attrs["x_names"] if n not in set(target_names)]
    statics, stacked = {}, {}
    b = None
    for n in ext_names:
        v = env.get(n)
        if v is None:
            continue
        if n in batch_led and hasattr(v, "ndim") and v.ndim >= 1:
            if b is None:
                b = v.shape[0]
            enforce(v.shape[0] == b,
                    f"pipeline feeds disagree on the batch dim: {n!r} has "
                    f"{v.shape[0]}, expected {b}", exc=InvalidArgumentError)
            stacked[n] = v
        else:
            statics[n] = v
    enforce(b is not None,
            "pipeline mode needs at least one batch-led feed to microbatch",
            exc=InvalidArgumentError)
    enforce(b % M == 0,
            f"pipeline mode: per-shard batch {b} is not divisible by "
            f"num_microbatches {M}; the schedule averages EQUAL-sized "
            f"microbatch losses, so feed a batch divisible by "
            f"dp * num_microbatches", exc=InvalidArgumentError)
    mb = b // M
    stacked = {n: v.reshape((M, mb) + v.shape[1:])
               for n, v in stacked.items()}

    # -- stage execution (shared by layout pass, forward, and backward) ---
    def _mb_env(mb_i):
        e = dict(statics)
        for n, v in stacked.items():
            e[n] = jax.lax.dynamic_index_in_dim(v, mb_i, axis=0,
                                                keepdims=False)
        return e

    def _stage_ctx(k, mb_i):
        # decorrelate randomness per (microbatch, stage) and make the
        # backward RECOMPUTE replay the forward's exact stream (same fold)
        return LowerCtx(rng_key=jax.random.fold_in(ctx.rng_key,
                                                   mb_i * K + k),
                        is_test=ctx.is_test, mesh=ctx.mesh,
                        extras=ctx.extras)

    def _run_stage(k, env2, bin_by_name, ctx2):
        """Run stage k's spliced op list; returns crossing out values (or
        None for the last stage). Boundary ops record "collective" spans
        carrying their cut's corr_id (trace-time provenance: the spliced
        send/recv pair shares the id, so a merged timeline pairs the
        producing and consuming stage lanes)."""
        from ..observability import tracing as _tracing
        out_vals = None
        for op in stage_ops[k]:
            if op.type == "pp_recv":
                with _tracing.span(
                        "collective", f"pp_recv/{op.attrs['cut']}",
                        stage=k, cut=op.attrs["cut"],
                        corr_id=op.attrs.get("corr_id", "")):
                    for n in op.outputs["Out"]:
                        env2[n] = bin_by_name[n]
            elif op.type == "pp_send":
                with _tracing.span(
                        "collective", f"pp_send/{op.attrs['cut']}",
                        stage=k, cut=op.attrs["cut"],
                        corr_id=op.attrs.get("corr_id", "")):
                    out_vals = [env2[n] for n in op.inputs["X"]]
            else:
                run_op(op, env2, block, ctx2)
        return out_vals

    # -- boundary layouts: abstract-interpret stages in order -------------
    layouts = []     # per cut: [(name, shape, dtype, offset, numel)]
    loss_aval = [None]
    cut_avals: Dict[str, jax.ShapeDtypeStruct] = {}
    for k in range(K):
        in_names = list(cut_names[k - 1]) if k > 0 else []
        in_avals = [cut_avals[n] for n in in_names]
        p_avals = [jax.ShapeDtypeStruct(p.shape, p.dtype) for p in params]
        mb_avals = [jax.ShapeDtypeStruct(v.shape[1:], v.dtype)
                    for v in stacked.values()]
        mb_keys = list(stacked.keys())

        def _abs(pv, bv, cv, _k=k, _in=in_names):
            env2 = dict(statics)
            env2.update(zip(mb_keys, bv))
            env2.update(zip(target_names, pv))
            ctx2 = LowerCtx(rng_key=jax.random.PRNGKey(0),
                            is_test=ctx.is_test, mesh=ctx.mesh,
                            extras=ctx.extras)
            outs = _run_stage(_k, env2, dict(zip(_in, cv)), ctx2)
            if _k == K - 1:
                return (env2[loss_name],)
            return tuple(outs)
        res = jax.eval_shape(_abs, tuple(p_avals), tuple(mb_avals),
                             tuple(in_avals))
        if k == K - 1:
            loss_aval[0] = res[0]
        else:
            off = 0
            lay = []
            for n, av in zip(cut_names[k], res):
                enforce(jnp.issubdtype(av.dtype, jnp.floating),
                        f"pipeline boundary var {n!r} has non-float dtype "
                        f"{av.dtype}; only floating activations may cross "
                        f"a stage cut", exc=InvalidArgumentError)
                numel = int(np.prod(av.shape)) if av.shape else 1
                lay.append((n, av.shape, av.dtype, off, numel))
                cut_avals[n] = av
                off += numel
            layouts.append(lay)
    S = max(1, max((lay[-1][3] + lay[-1][4] for lay in layouts),
                   default=1))

    def _pack(vals):
        # packing order == the send op's input order == the cut layout
        flat = jnp.concatenate(
            [v.astype(f32).reshape(-1) for v in vals]) if vals else \
            jnp.zeros((0,), f32)
        return jnp.pad(flat, (0, S - flat.shape[0]))

    def _unpack(buf, lay):
        return {n: buf[off:off + numel].reshape(shape).astype(dtype)
                for n, shape, dtype, off, numel in lay}

    # -- per-stage forward as a pure fn of (params, boundary-in) ----------
    def _stage_fwd(k, pvals, bin_flat, mb_i):
        env2 = _mb_env(mb_i)
        env2.update(zip(target_names, pvals))
        ctx2 = _stage_ctx(k, mb_i)
        bin_by_name = _unpack(bin_flat, layouts[k - 1]) if k > 0 else {}
        out_vals = _run_stage(k, env2, bin_by_name, ctx2)
        if k == K - 1:
            return (jnp.zeros((S,), f32),
                    jnp.asarray(env2[loss_name], f32).reshape(()))
        return _pack(out_vals), jnp.zeros((), f32)

    zero_params = tuple(jnp.zeros(p.shape, p.dtype) for p in params)
    zero_buf = jnp.zeros((S,), f32)
    zero_loss = jnp.zeros((), f32)

    def _fwd_branch(k):
        def br(pvals, bin_f, bin_b, gin, fm, bm):
            bout, loss = _stage_fwd(k, pvals, bin_f, fm)
            return bout, loss, zero_buf, zero_params
        return br

    def _bwd_branch(k):
        def br(pvals, bin_f, bin_b, gin, fm, bm):
            # recompute stage k's forward for microbatch bm from the
            # stashed boundary input, then pull the incoming boundary
            # gradient (the 1/M loss seed on the last stage) back through
            def f(pv, bf):
                return _stage_fwd(k, pv, bf, bm)
            _, vjp_fn = jax.vjp(f, pvals, bin_b)
            ct_bout = gin if k < K - 1 else zero_buf
            ct_loss = (jnp.full((), 1.0 / M, f32) if k == K - 1
                       else zero_loss)
            gp, gbin = vjp_fn((ct_bout, ct_loss))
            return zero_buf, zero_loss, gbin, gp
        return br

    def _idle_branch(pvals, bin_f, bin_b, gin, fm, bm):
        return zero_buf, zero_loss, zero_buf, zero_params

    branches = ([_fwd_branch(k) for k in range(K)]
                + [_bwd_branch(k) for k in range(K)]
                + [_idle_branch])

    # -- the tick scan ----------------------------------------------------
    sched = build_schedule(attrs["schedule"], M, K)
    T = sched.ticks
    d_a, d_g = sched.act_stash_depth, sched.grad_stash_depth
    fwd_tbl = jnp.asarray(sched.fwd_mb)
    bwd_tbl = jnp.asarray(sched.bwd_mb)
    arr_a_tbl = jnp.asarray(sched.arr_act)
    arr_g_tbl = jnp.asarray(sched.arr_grad)
    perm_fwd = [(i, i + 1) for i in range(K - 1)]
    perm_bwd = [(i, i - 1) for i in range(1, K)]

    def tick(carry, t):
        stash_a, stash_g, loss_sum, gacc = carry
        fm = fwd_tbl[t, pp_idx]
        bm = bwd_tbl[t, pp_idx]
        fi = jnp.clip(fm, 0, M - 1)
        bi = jnp.clip(bm, 0, M - 1)
        bin_f = stash_a[jnp.mod(fi, d_a)]
        bin_b = stash_a[jnp.mod(bi, d_a)]
        gin = stash_g[jnp.mod(bi, d_g)]
        idx = jnp.where(fm >= 0, pp_idx,
                        jnp.where(bm >= 0, K + pp_idx, 2 * K))
        bout, loss_c, gbin, gp = jax.lax.switch(
            idx, branches, params, bin_f, bin_b, gin, fi, bi)
        # one boundary-activation shift + one boundary-gradient shift per
        # tick (the "one send/recv pair per boundary per tick" the HLO
        # census asserts)
        act_in = jax.lax.ppermute(bout, axis, perm_fwd)
        grad_in = jax.lax.ppermute(gbin, axis, perm_bwd)
        am = arr_a_tbl[t, pp_idx]
        gm = arr_g_tbl[t, pp_idx]
        ai = jnp.mod(jnp.clip(am, 0, None), d_a)
        stash_a = stash_a.at[ai].set(
            jnp.where(am >= 0, act_in, stash_a[ai]))
        gi = jnp.mod(jnp.clip(gm, 0, None), d_g)
        stash_g = stash_g.at[gi].set(
            jnp.where(gm >= 0, grad_in, stash_g[gi]))
        return (stash_a, stash_g, loss_sum + loss_c,
                tuple(a + g for a, g in zip(gacc, gp))), None

    init = (jnp.zeros((d_a, S), f32), jnp.zeros((d_g, S), f32),
            zero_loss, zero_params)
    (s_a, s_g, loss_sum, gacc), _ = jax.lax.scan(
        tick, init, jnp.arange(T, dtype=jnp.int32))

    # only the last stage accumulated loss; each stage holds its own
    # params' grad contributions — psum over pp gives every stage the
    # totals (zeros elsewhere), keeping the replicated optimizer exact
    loss_total = jax.lax.psum(loss_sum, axis) / M
    grads = jax.lax.psum(gacc, axis)
    if attrs.get("reduce_dp") and dp_axis:
        grads = jax.lax.pmean(grads, dp_axis)
    loss_val = loss_total.astype(loss_aval[0].dtype).reshape(
        loss_aval[0].shape)
    env[loss_name] = loss_val
    env[grad_var_name(loss_name)] = jnp.ones_like(loss_val)
    for n, g in zip(target_names, grads):
        env[grad_var_name(n)] = g


def pp_boundary_wire_bytes(program, microbatch_rows: int) -> Optional[Dict]:
    """Per-device interconnect bytes per STEP of a pipeline-partitioned
    program's boundary transfers — the analytic side the HLO census is
    checked against (tests/test_pipeline_parallel.py), same ring-accounting
    discipline as grad_comm.analytic_wire_bytes. The engine moves one
    activation buffer and one gradient buffer of S f32 (the max cut size)
    through a collective-permute EVERY tick, idle or not — so per step:
    2 * ticks * S * 4 bytes. None for non-partitioned programs."""
    if not getattr(program, "_pp_applied", False):
        return None
    block = program.global_block()
    region = next((op for op in block.ops if op.type == PP_REGION_TYPE),
                  None)
    if region is None:
        return None
    cut_numels = []
    for op in block.ops:
        if op.type != "pp_send":
            continue
        total = 0
        for n in op.inputs["X"]:
            v = block.var(n)
            shape = list(v.shape or ())
            numel = 1
            for d in shape:
                numel *= (microbatch_rows if d == -1 else int(d))
            total += numel
        cut_numels.append(total)
    if not cut_numels:
        return None
    s = max(cut_numels)
    sched = build_schedule(region.attrs["schedule"],
                           region.attrs["num_microbatches"],
                           region.attrs["num_stages"])
    per_tick = 2 * s * 4                       # act shift + grad shift
    return {"buffer_numel": s,
            "cut_numels": cut_numels,
            "ticks_per_step": sched.ticks,
            "pp_boundary_bytes": per_tick * sched.ticks}


# register the region runner with the block planner
from ..framework import lowering as _lowering  # noqa: E402

_lowering.REGION_RUNNERS[PP_REGION_TYPE] = run_pp_region
