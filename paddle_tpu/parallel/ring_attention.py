"""Ring attention: exact attention over sequences sharded across devices.

NEW capability with no reference analogue (SURVEY.md §5 "long context": the
reference's story is LoD ragged batching, not sequence sharding). Design is
the ring/flash formulation: Q,K,V are sharded along the sequence dim over the
`sp` mesh axis; each device computes blockwise attention against its local KV
block while rotating KV blocks around the ICI ring with `ppermute`,
accumulating the softmax online, so the full [T, T] score matrix never
materializes and comm overlaps compute.

v2 (VERDICT r4 #2): each ring step's local block runs through the SAME
Pallas flash kernels as single-device attention (`ops/pallas_kernels.py`) —
O(t_local) memory per block, per-tile dead-block skipping inside the kernel
— and the `_block_alive` idea is lifted to ring granularity: a causal ring
step whose held KV block is entirely in the query block's future (or a
packed step whose segment-id ranges cannot overlap) is a `lax.switch` branch
that computes NOTHING. A causal ring therefore executes n(n+1)/2 of the n^2
block computations (~half the FLOPs), matching the flash kernel's own
causal block skipping. Gradients are a ring-level `jax.custom_vjp`: the
backward re-runs the ring with the flash backward kernels against the
GLOBAL logsumexp/delta residuals (flash backward is block-decomposable),
rotating dk/dv accumulators home with the KV blocks.

Cost: n ring steps of flash-kernel block attention + (n-1) KV ppermutes on
the forward; (n-1) KV + n dKV ppermutes on the backward — exact, not
approximate, attention.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .mesh import (DATA_AXIS, SEQUENCE_AXIS, DeviceMesh,  # noqa: F401
                   shard_map)

_NEG_INF = -1e30


def _block_attn(q, k, v, bias, m_prev, l_prev, o_prev, scale):
    """One online-softmax block update (reference composite; kept as the
    semantic spec the kernels are tested against — test_pallas_attention
    matches the flash kernel to this block math).

    q: [B, Tq, H, D]; k,v: [B, Tk, H, D]; bias: [B, 1|H, Tq, Tk] additive
    mask (0 / -inf); m,l,o running max / denom / numerator.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    m_cur = jnp.max(s, axis=-1)                      # [B, H, Tq]
    m_new = jnp.maximum(m_prev, m_cur)
    # guard fully-masked rows: keep exp finite
    p = jnp.exp(s - m_new[..., None])                # [B, H, Tq, Tk]
    l_cur = jnp.sum(p, axis=-1)
    corr = jnp.exp(m_prev - m_new)
    l_new = l_prev * corr + l_cur
    o_cur = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    o_new = o_prev * corr.transpose(0, 2, 1)[..., None] + o_cur
    return m_new, l_new, o_new


# ---------------------------------------------------------------------------
# per-block forward/backward, shared flash-kernel path + XLA fallback
# ---------------------------------------------------------------------------


def _block_fwd(q, k, v, scale, causal, q_ids, kv_ids, backend, block_q,
               block_k):
    """One ring block: q,k,v [B,H,t,D] -> (o f32 [B,H,t,D], lse f32
    [B,H,t]). A query row with no visible key gets o=0, lse=-inf (the flash
    kernels' convention), which the logsumexp merge treats as weight 0."""
    if backend == "xla":
        f32 = jnp.float32
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(f32),
                       k.astype(f32)) * scale
        valid = _block_valid(s.shape, causal, q_ids, kv_ids)
        if valid is not None:
            s = jnp.where(valid, s, _NEG_INF)
        m = jnp.max(s, axis=-1)                      # [B,H,t]
        p = jnp.where(s > _NEG_INF / 2, jnp.exp(s - m[..., None]), 0.0)
        l = jnp.sum(p, axis=-1)
        lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)),
                        _NEG_INF)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(f32))
        o = o / jnp.maximum(l, 1e-30)[..., None]
        return o, lse
    from ..ops.pallas_kernels import _flash_attention_pallas
    seg = (q_ids, kv_ids) if q_ids is not None else None
    o, lse = _flash_attention_pallas(
        q, k, v, scale, causal, block_q, block_k,
        interpret=(backend == "pallas_interpret"), with_lse=True,
        segment_ids=seg)
    return o.astype(jnp.float32), lse


def _block_valid(s_shape, causal, q_ids, kv_ids):
    B, H, tq, tk = s_shape
    valid = None
    if causal:
        valid = jnp.tril(jnp.ones((tq, tk), bool))[None, None]
    if q_ids is not None:
        same = (q_ids[:, :, None] == kv_ids[:, None, :])[:, None]
        valid = same if valid is None else valid & same
    return valid


def _block_bwd(q, k, v, do, lse, delta, scale, causal, q_ids, kv_ids,
               backend, block_q, block_k):
    """One ring block backward against GLOBAL residuals: returns
    (dq, dk, dv) each [B,H,t,D] in q/k/v dtype. p = exp(s - lse) is the
    block's slice of the global softmax, so per-block grads sum to the
    exact global gradient."""
    if backend == "xla":
        f32 = jnp.float32
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(f32),
                       k.astype(f32)) * scale
        valid = _block_valid(s.shape, causal, q_ids, kv_ids)
        p = jnp.exp(s - lse[..., None])
        if valid is not None:
            p = jnp.where(valid, p, 0.0)
        dof = do.astype(f32)
        dv = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
        dp = jnp.einsum("bhqd,bhkd->bhqk", dof, v.astype(f32))
        ds = p * (dp - delta[..., None]) * scale
        dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(f32))
        dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(f32))
        return (dq.astype(q.dtype), dk.astype(k.dtype),
                dv.astype(v.dtype))
    from ..ops.pallas_kernels import _flash_attention_bwd_pallas
    seg = (q_ids, kv_ids) if q_ids is not None else None
    return _flash_attention_bwd_pallas(
        q, k, v, None, lse, do, scale, causal, block_q, block_k,
        interpret=(backend == "pallas_interpret"), segment_ids=seg,
        delta=delta)


def _as_varying_as(x, *refs):
    """Mark a freshly-created constant as device-varying over every mesh
    axis any of `refs` varies over — lax.switch requires all branches to
    produce identical vma types under shard_map, and the dead branch's
    zeros would otherwise come out replicated."""
    axes = set()
    for r in refs:
        axes |= set(getattr(r.aval, "vma", ()) or ())
    if not axes or not hasattr(jax.lax, "pcast"):
        # jax < 0.7 has no vma tracking (avals carry no .vma, so `axes` is
        # empty there anyway) — nothing to mark
        return x
    return jax.lax.pcast(x, tuple(sorted(axes)), to="varying")


def _merge(o_acc, lse_acc, o_r, lse_r):
    """Online logsumexp merge of a new block's normalized output: keeps
    o_acc correctly normalized over every block seen so far."""
    lse_new = jnp.logaddexp(lse_acc, lse_r)
    w_acc = jnp.exp(lse_acc - lse_new)[..., None]
    w_r = jnp.exp(lse_r - lse_new)[..., None]
    return o_acc * w_acc + o_r * w_r, lse_new


def _step_case(r, idx, n, causal, seg_q_minmax, seg_blk):
    """Ring-step branch index: 0 = full block, 1 = diagonal (causal mask
    applies inside the block), 2 = dead (skip the computation entirely).
    The causal part is the ring-granularity `_block_alive`: a held KV
    block from src > idx is entirely in every local query's future. The
    segment part mirrors the kernels' range-overlap test: if no row's
    [min,max] id ranges overlap, no (q, key) pair can match."""
    src = (idx - r) % n
    if causal:
        case = jnp.where(src == idx, 1, jnp.where(src < idx, 0, 2))
    else:
        case = jnp.int32(0)
    if seg_blk is not None:
        q_min, q_max = seg_q_minmax
        kv_min = jnp.min(seg_blk, axis=1)            # [B]
        kv_max = jnp.max(seg_blk, axis=1)
        overlap = jnp.any((q_max >= kv_min) & (q_min <= kv_max))
        case = jnp.where(overlap, case, 2)
    return case


def _ring_fwd_scan(q, k, v, segment_ids, axis_name, causal, scale, backend,
                   block_q, block_k):
    """Per-shard forward ring. q,k,v [B,H,t,D] (head-major). Returns
    (o f32, lse f32, live int32) with live = number of ring steps whose
    block computation actually executed (the skip-evidence counter)."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, H, t, D = q.shape

    from .collective import ring_perm
    perm = ring_perm(int(n))

    o_acc = jnp.zeros((B, H, t, D), jnp.float32)
    lse_acc = jnp.full((B, H, t), _NEG_INF, jnp.float32)
    live = jnp.int32(0)
    seg_q_minmax = None
    if segment_ids is not None:
        seg_q_minmax = (jnp.min(segment_ids, axis=1),
                        jnp.max(segment_ids, axis=1))

    k_blk, v_blk, seg_blk = k, v, segment_ids
    for r in range(int(n)):
        case = _step_case(r, idx, n, causal, seg_q_minmax, seg_blk)

        def _full(kb, vb, sb):
            return _block_fwd(q, kb, vb, scale, False, segment_ids, sb,
                              backend, block_q, block_k)

        def _diag(kb, vb, sb):
            return _block_fwd(q, kb, vb, scale, True, segment_ids, sb,
                              backend, block_q, block_k)

        def _dead(kb, vb, sb):
            return (_as_varying_as(jnp.zeros((B, H, t, D), jnp.float32),
                                   q, kb),
                    _as_varying_as(jnp.full((B, H, t), _NEG_INF,
                                            jnp.float32), q, kb))

        if segment_ids is None:
            # keep branch signatures uniform; sb unused
            o_r, lse_r = jax.lax.switch(
                case, [lambda kb, vb: _full(kb, vb, None),
                       lambda kb, vb: _diag(kb, vb, None),
                       lambda kb, vb: _dead(kb, vb, None)], k_blk, v_blk)
        else:
            o_r, lse_r = jax.lax.switch(
                case, [_full, _diag, _dead], k_blk, v_blk, seg_blk)
        o_acc, lse_acc = _merge(o_acc, lse_acc, o_r, lse_r)
        live = live + jnp.where(case != 2, 1, 0).astype(jnp.int32)

        if r < int(n) - 1:                           # n-1 KV hops exactly
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            if seg_blk is not None:
                seg_blk = jax.lax.ppermute(seg_blk, axis_name, perm)
    return o_acc, lse_acc, live


def _ring_bwd_scan(q, k, v, segment_ids, lse, delta, do, axis_name, causal,
                   scale, backend, block_q, block_k):
    """Per-shard backward ring against global (lse, delta). dk/dv
    accumulators rotate WITH the KV blocks and take the n-th hop home."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, H, t, D = q.shape

    from .collective import ring_perm
    perm = ring_perm(int(n))

    dq_acc = jnp.zeros((B, H, t, D), jnp.float32)
    seg_q_minmax = None
    if segment_ids is not None:
        seg_q_minmax = (jnp.min(segment_ids, axis=1),
                        jnp.max(segment_ids, axis=1))

    k_blk, v_blk, seg_blk = k, v, segment_ids
    # dKV accumulators ride the ring in f32: bf16 accumulation across n
    # partial contributions would lose the low bits of the sum
    dk_blk = jnp.zeros(k.shape, jnp.float32)
    dv_blk = jnp.zeros(v.shape, jnp.float32)
    for r in range(int(n)):
        case = _step_case(r, idx, n, causal, seg_q_minmax, seg_blk)

        def _full(kb, vb, sb):
            return _block_bwd(q, kb, vb, do, lse, delta, scale, False,
                              segment_ids, sb, backend, block_q, block_k)

        def _diag(kb, vb, sb):
            return _block_bwd(q, kb, vb, do, lse, delta, scale, True,
                              segment_ids, sb, backend, block_q, block_k)

        def _dead(kb, vb, sb):
            return (_as_varying_as(jnp.zeros((B, H, t, D), q.dtype),
                                   q, kb, do),
                    _as_varying_as(jnp.zeros((B, H, t, D), k.dtype),
                                   q, kb, do),
                    _as_varying_as(jnp.zeros((B, H, t, D), v.dtype),
                                   q, kb, do))

        if segment_ids is None:
            dq_r, dk_r, dv_r = jax.lax.switch(
                case, [lambda kb, vb: _full(kb, vb, None),
                       lambda kb, vb: _diag(kb, vb, None),
                       lambda kb, vb: _dead(kb, vb, None)], k_blk, v_blk)
        else:
            dq_r, dk_r, dv_r = jax.lax.switch(
                case, [_full, _diag, _dead], k_blk, v_blk, seg_blk)
        dq_acc = dq_acc + dq_r.astype(jnp.float32)
        dk_blk = dk_blk + dk_r.astype(jnp.float32)
        dv_blk = dv_blk + dv_r.astype(jnp.float32)

        if r < int(n) - 1:
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            if seg_blk is not None:
                seg_blk = jax.lax.ppermute(seg_blk, axis_name, perm)
        # the dKV accumulators take ALL n hops: after the last compute the
        # held block is (idx+1)'s, one more rotation returns it home
        dk_blk = jax.lax.ppermute(dk_blk, axis_name, perm)
        dv_blk = jax.lax.ppermute(dv_blk, axis_name, perm)
    return (dq_acc.astype(q.dtype), dk_blk.astype(k.dtype),
            dv_blk.astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _ring_attention(q, k, v, segment_ids, axis_name, causal, scale, backend,
                    block_q, block_k):
    o, _, _ = _ring_fwd_scan(q, k, v, segment_ids, axis_name, causal, scale,
                             backend, block_q, block_k)
    return o.astype(q.dtype)


def _ring_attention_fwd(q, k, v, segment_ids, axis_name, causal, scale,
                        backend, block_q, block_k):
    o, lse, _ = _ring_fwd_scan(q, k, v, segment_ids, axis_name, causal,
                               scale, backend, block_q, block_k)
    out = o.astype(q.dtype)
    return out, (q, k, v, segment_ids, out, lse)


def _ring_attention_bwd(axis_name, causal, scale, backend, block_q, block_k,
                        res, g):
    q, k, v, segment_ids, o, lse = res
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    dq, dk, dv = _ring_bwd_scan(q, k, v, segment_ids, lse, delta, g,
                                axis_name, causal, scale, backend, block_q,
                                block_k)
    return dq, dk, dv, None


_ring_attention.defvjp(_ring_attention_fwd, _ring_attention_bwd)


def _resolve_backend(backend):
    if backend is not None:
        return backend
    from ..ops.pallas_kernels import _auto_backend
    return _auto_backend()


def ring_attention(q, k, v, *, axis_name: str = SEQUENCE_AXIS,
                   causal: bool = False, scale: Optional[float] = None,
                   segment_ids=None, backend: Optional[str] = None,
                   block_q: int = 512, block_k: int = 1024,
                   with_stats: bool = False):
    """Per-shard ring attention body. Must run inside shard_map with q/k/v
    sequence-sharded: q,k,v: [B, T_local, H, D].

    segment_ids: optional [B, T_local] int array (packed-batch masking — the
    static-shape translation of the reference's LoD batches, SURVEY.md §5).
    backend: None = auto (Pallas flash kernels on TPU, XLA composite
    elsewhere); "pallas_interpret" runs the kernels through the pallas
    interpreter (CPU-testable); "xla" forces the composite blocks.
    with_stats: also return the number of ring-step block computations this
    shard actually executed (dead causal/segment steps are skipped whole).
    """
    backend = _resolve_backend(backend)
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    seg = None if segment_ids is None else jnp.asarray(segment_ids,
                                                       jnp.int32)
    # ring API carries [B, t, H, D]; the kernels run head-major [B, H, t, D]
    qh = jnp.transpose(q, (0, 2, 1, 3))
    kh = jnp.transpose(k, (0, 2, 1, 3))
    vh = jnp.transpose(v, (0, 2, 1, 3))
    if with_stats:
        o, _, live = _ring_fwd_scan(qh, kh, vh, seg, axis_name, causal,
                                    scale, backend, block_q, block_k)
        return jnp.transpose(o.astype(q.dtype), (0, 2, 1, 3)), live
    out = _ring_attention(qh, kh, vh, seg, axis_name, causal, scale,
                          backend, block_q, block_k)
    return jnp.transpose(out, (0, 2, 1, 3))


def ring_attention_sharded(mesh: DeviceMesh, q, k, v, *, causal=False,
                           scale=None, segment_ids=None, backend=None,
                           block_q: int = 512, block_k: int = 1024):
    """Entry point from the annotate-and-partition world: q,k,v [B, T, H, D]
    (any sharding); returns attention output with T sharded over sp."""
    if SEQUENCE_AXIS not in mesh.axes:
        raise ValueError(
            f"ring attention requires a {SEQUENCE_AXIS!r} axis in the mesh "
            f"(got axes {tuple(mesh.axes)}); for unsharded sequences use "
            f"plain attention")
    in_spec = mesh.pspec(DATA_AXIS, SEQUENCE_AXIS, None, None)
    seg_spec = mesh.pspec(DATA_AXIS, SEQUENCE_AXIS)
    backend = _resolve_backend(backend)
    # the pallas INTERPRETER's discharge path trips a jax vma bug inside
    # checked shard_map (dynamic_slice "varying manual axes" mismatch);
    # disable the check only for that test backend — the production
    # pallas/xla paths keep shard_map's varying-axes validation
    check_vma = backend != "pallas_interpret"

    if segment_ids is None:
        def body(q, k, v):
            return ring_attention(q, k, v, causal=causal, scale=scale,
                                  backend=backend, block_q=block_q,
                                  block_k=block_k)
        f = shard_map(body, mesh=mesh.jax_mesh,
                      in_specs=(in_spec, in_spec, in_spec),
                      out_specs=in_spec, check_vma=check_vma)
        return f(q, k, v)

    def body(q, k, v, seg):
        return ring_attention(q, k, v, causal=causal, scale=scale,
                              segment_ids=seg, backend=backend,
                              block_q=block_q, block_k=block_k)
    f = shard_map(body, mesh=mesh.jax_mesh,
                  in_specs=(in_spec, in_spec, in_spec, seg_spec),
                  out_specs=in_spec, check_vma=check_vma)
    return f(q, k, v, segment_ids)


def ring_attention_live_blocks(mesh: DeviceMesh, q, k, v, *, causal=False,
                               scale=None, segment_ids=None, backend=None):
    """Diagnostic entry: run the forward ring and return (out, total number
    of block computations executed across all shards). A causal ring over n
    shards executes n(n+1)/2 of the n^2 blocks; a non-causal ring executes
    all n^2. Evidence hook for the dead-step skipping tests/benches."""
    in_spec = mesh.pspec(DATA_AXIS, SEQUENCE_AXIS, None, None)
    seg_spec = mesh.pspec(DATA_AXIS, SEQUENCE_AXIS)
    specs = [in_spec, in_spec, in_spec]
    args = [q, k, v]
    if segment_ids is not None:
        specs.append(seg_spec)
        args.append(segment_ids)
    backend = _resolve_backend(backend)

    # sum over the axes the computation is actually SHARDED on (batch over
    # DATA, sequence over SEQUENCE): with a dp-sharded batch and
    # heterogeneous packing, different data shards skip different numbers
    # of steps — a SEQUENCE_AXIS-only psum would report one data shard's
    # count as the mesh total. But axes the body is REPLICATED over (e.g.
    # a tensor-parallel axis absent from in_specs) must NOT be summed:
    # each replica holds the identical count, and summing replicas would
    # inflate the diagnostic by the replication factor (ADVICE r5 #1).
    shard_axes = tuple(a for a in (DATA_AXIS, SEQUENCE_AXIS)
                       if a in mesh.axes)

    def body(*xs):
        seg = xs[3] if len(xs) > 3 else None
        out, live = ring_attention(
            xs[0], xs[1], xs[2], causal=causal, scale=scale,
            segment_ids=seg, backend=backend, with_stats=True)
        return out, jax.lax.psum(live, shard_axes)

    f = shard_map(body, mesh=mesh.jax_mesh, in_specs=tuple(specs),
                  out_specs=(in_spec, mesh.pspec()),
                  check_vma=backend != "pallas_interpret")
    out, live = f(*args)
    return out, int(jnp.max(live))
