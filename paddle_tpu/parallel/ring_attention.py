"""Ring attention: exact attention over sequences sharded across devices.

NEW capability with no reference analogue (SURVEY.md §5 "long context": the
reference's story is LoD ragged batching, not sequence sharding). Design is
the ring/flash formulation: Q,K,V are sharded along the sequence dim over the
`sp` mesh axis; each device computes blockwise attention against its local KV
block while rotating KV blocks around the ICI ring with `ppermute`,
accumulating the softmax online (running max + running denominator), so the
full [T, T] score matrix never materializes and comm overlaps compute.

Cost: n_ring steps of [B, T/n, T/n] matmuls + (n-1) KV ppermutes — exact, not
approximate, attention.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from .mesh import DATA_AXIS, SEQUENCE_AXIS, DeviceMesh

_NEG_INF = -1e30


def _block_attn(q, k, v, bias, m_prev, l_prev, o_prev, scale):
    """One online-softmax block update.

    q: [B, Tq, H, D]; k,v: [B, Tk, H, D]; bias: [B, 1|H, Tq, Tk] additive
    mask (0 / -inf); m,l,o running max / denom / numerator.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    m_cur = jnp.max(s, axis=-1)                      # [B, H, Tq]
    m_new = jnp.maximum(m_prev, m_cur)
    # guard fully-masked rows: keep exp finite
    p = jnp.exp(s - m_new[..., None])                # [B, H, Tq, Tk]
    l_cur = jnp.sum(p, axis=-1)
    corr = jnp.exp(m_prev - m_new)
    l_new = l_prev * corr + l_cur
    o_cur = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    o_new = o_prev * corr.transpose(0, 2, 1)[..., None] + o_cur
    return m_new, l_new, o_new


def ring_attention(q, k, v, *, axis_name: str = SEQUENCE_AXIS,
                   causal: bool = False, scale: Optional[float] = None,
                   segment_ids=None):
    """Per-shard ring attention body. Must run inside shard_map with q/k/v
    sequence-sharded: q,k,v: [B, T_local, H, D].

    segment_ids: optional [B, T_local] int array (packed-batch masking — the
    static-shape translation of the reference's LoD batches, SURVEY.md §5).
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, t_local, H, D = q.shape
    scale = scale if scale is not None else 1.0 / (D ** 0.5)

    q_pos = idx * t_local + jnp.arange(t_local)          # global positions

    m0 = jnp.full((B, H, t_local), _NEG_INF, q.dtype)
    l0 = jnp.zeros((B, H, t_local), q.dtype)
    o0 = jnp.zeros_like(q)

    from .collective import ring_perm
    perm = ring_perm(n)

    def ring_step(r, carry):
        m, l, o, k_blk, v_blk, seg_blk = carry
        # KV block currently held came from shard (idx - r) mod n
        src = (idx - r) % n
        k_pos = src * t_local + jnp.arange(t_local)
        bias = jnp.zeros((1, 1, t_local, t_local), q.dtype)
        if causal:
            causal_mask = q_pos[:, None] >= k_pos[None, :]
            bias = jnp.where(causal_mask[None, None], 0.0, _NEG_INF)
        if seg_blk is not None and segment_ids is not None:
            same = (segment_ids[:, :, None] == seg_blk[:, None, :])
            seg_bias = jnp.where(same[:, None], 0.0, _NEG_INF)
            bias = bias + seg_bias
        m, l, o = _block_attn(q, k_blk, v_blk, bias, m, l, o, scale)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        if seg_blk is not None:
            seg_blk = jax.lax.ppermute(seg_blk, axis_name, perm)
        return m, l, o, k_blk, v_blk, seg_blk

    # The ring is unrolled in Python: n (the mesh axis size) is a trace-time
    # constant, the unroll length equals the number of ICI hops, and unrolling
    # keeps reverse-mode AD through ppermute straightforward.
    m, l, o, k_blk, v_blk, seg_blk = m0, l0, o0, k, v, segment_ids
    for r in range(n):
        m, l, o, k_blk, v_blk, seg_blk = ring_step(
            r, (m, l, o, k_blk, v_blk, seg_blk))
    l = jnp.maximum(l, 1e-20)
    return o / l.transpose(0, 2, 1)[..., None]


def ring_attention_sharded(mesh: DeviceMesh, q, k, v, *, causal=False,
                           scale=None, segment_ids=None):
    """Entry point from the annotate-and-partition world: q,k,v [B, T, H, D]
    (any sharding); returns attention output with T sharded over sp."""
    if SEQUENCE_AXIS not in mesh.axes:
        raise ValueError(
            f"ring attention requires a {SEQUENCE_AXIS!r} axis in the mesh "
            f"(got axes {tuple(mesh.axes)}); for unsharded sequences use "
            f"plain attention")
    in_spec = mesh.pspec(DATA_AXIS, SEQUENCE_AXIS, None, None)
    seg_spec = mesh.pspec(DATA_AXIS, SEQUENCE_AXIS)

    if segment_ids is None:
        def body(q, k, v):
            return ring_attention(q, k, v, causal=causal, scale=scale)
        f = shard_map(body, mesh=mesh.jax_mesh,
                      in_specs=(in_spec, in_spec, in_spec),
                      out_specs=in_spec)
        return f(q, k, v)

    def body(q, k, v, seg):
        return ring_attention(q, k, v, causal=causal, scale=scale,
                              segment_ids=seg)
    f = shard_map(body, mesh=mesh.jax_mesh,
                  in_specs=(in_spec, in_spec, in_spec, seg_spec),
                  out_specs=in_spec)
    return f(q, k, v, segment_ids)
