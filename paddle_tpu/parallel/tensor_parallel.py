"""Tensor (model) parallelism helpers.

NEW first-class capability with no reference analogue (SURVEY.md §2.3: the
reference has no tensor-sharded matmul). Design is the standard TPU/Megatron
formulation expressed the XLA-SPMD way: parameters carry shardings over the
model axis and activations carry `with_sharding_constraint` annotations; the
partitioner inserts the all-reduce/all-gather on ICI.

Column-parallel: W [in, out] sharded on `out` → local matmul, activations
sharded on feature dim, no comm. Row-parallel: W [in, out] sharded on `in` →
local partial matmul + all-reduce (psum) on the output. A column→row pair
(e.g. MLP up/down proj, attention qkv/out proj) costs exactly one all-reduce
per direction — the Megatron recipe.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .mesh import MODEL_AXIS, SEQUENCE_AXIS


def shard(x, *spec):
    """Annotate an activation with a PartitionSpec (axis names not present in
    the ambient mesh are dropped by jax automatically only for AUTO axes, so
    callers should build specs against the mesh in use; `DeviceMesh.sharding`
    handles filtering for explicit shardings)."""
    return jax.lax.with_sharding_constraint(x, P(*spec))


def column_parallel_spec(ndim: int):
    """Sharding spec for a weight whose LAST dim is split over the model
    axis (qkv proj, MLP up-proj)."""
    return P(*([None] * (ndim - 1) + [MODEL_AXIS]))


def row_parallel_spec(ndim: int):
    """Sharding spec for a weight whose FIRST-of-last-two dim is split over
    the model axis (out proj, MLP down-proj)."""
    assert ndim >= 2
    return P(*([None] * (ndim - 2) + [MODEL_AXIS, None]))


def column_parallel_matmul(x, w, b: Optional[jnp.ndarray] = None):
    """y = x @ w with w sharded on its output dim. Output activations are
    feature-sharded; no collective."""
    w = jax.lax.with_sharding_constraint(w, column_parallel_spec(w.ndim))
    y = jnp.matmul(x, w)
    y = shard(y, *([None] * (y.ndim - 1)), MODEL_AXIS)
    if b is not None:
        y = y + b
    return y


def row_parallel_matmul(x, w, b: Optional[jnp.ndarray] = None):
    """y = x @ w with w sharded on its input dim; x arrives feature-sharded
    from a preceding column-parallel layer. XLA inserts the psum."""
    w = jax.lax.with_sharding_constraint(w, row_parallel_spec(w.ndim))
    x = shard(x, *([None] * (x.ndim - 1)), MODEL_AXIS)
    y = jnp.matmul(x, w)
    y = shard(y, *([None] * y.ndim))  # replicated feature dim (post-psum)
    if b is not None:
        y = y + b
    return y


def sequence_shard(x, batch_axis_spec="dp"):
    """Sequence-parallel activation layout [B, T, D] with T split over the
    sequence axis — used between transformer blocks so layernorm/dropout/
    elementwise work is also divided (Megatron-SP). Attention/MLP regions
    re-gather via their own shardings."""
    return shard(x, batch_axis_spec, SEQUENCE_AXIS, None)
