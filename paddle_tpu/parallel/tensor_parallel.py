"""Tensor (model) parallelism helpers.

NEW first-class capability with no reference analogue (SURVEY.md §2.3: the
reference has no tensor-sharded matmul). Design is the standard TPU/Megatron
formulation expressed the XLA-SPMD way: parameters carry shardings over the
model axis and activations carry `with_sharding_constraint` annotations; the
partitioner inserts the all-reduce/all-gather on ICI.

Column-parallel: W [in, out] sharded on `out` → local matmul, activations
sharded on feature dim, no comm. Row-parallel: W [in, out] sharded on `in` →
local partial matmul + all-reduce (psum) on the output. A column→row pair
(e.g. MLP up/down proj, attention qkv/out proj) costs exactly one all-reduce
per direction — the Megatron recipe.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .mesh import MODEL_AXIS, SEQUENCE_AXIS


def shard(x, *spec):
    """Annotate an activation with a PartitionSpec (axis names not present in
    the ambient mesh are dropped by jax automatically only for AUTO axes, so
    callers should build specs against the mesh in use; `DeviceMesh.sharding`
    handles filtering for explicit shardings)."""
    return jax.lax.with_sharding_constraint(x, P(*spec))


def column_parallel_spec(ndim: int):
    """Sharding spec for a weight whose LAST dim is split over the model
    axis (qkv proj, MLP up-proj)."""
    return P(*([None] * (ndim - 1) + [MODEL_AXIS]))


def row_parallel_spec(ndim: int):
    """Sharding spec for a weight whose FIRST-of-last-two dim is split over
    the model axis (out proj, MLP down-proj)."""
    assert ndim >= 2
    return P(*([None] * (ndim - 2) + [MODEL_AXIS, None]))


def column_parallel_matmul(x, w, b: Optional[jnp.ndarray] = None):
    """y = x @ w with w sharded on its output dim. Output activations are
    feature-sharded; no collective."""
    w = jax.lax.with_sharding_constraint(w, column_parallel_spec(w.ndim))
    y = jnp.matmul(x, w)
    y = shard(y, *([None] * (y.ndim - 1)), MODEL_AXIS)
    if b is not None:
        y = y + b
    return y


def row_parallel_matmul(x, w, b: Optional[jnp.ndarray] = None):
    """y = x @ w with w sharded on its input dim; x arrives feature-sharded
    from a preceding column-parallel layer. XLA inserts the psum."""
    w = jax.lax.with_sharding_constraint(w, row_parallel_spec(w.ndim))
    x = shard(x, *([None] * (x.ndim - 1)), MODEL_AXIS)
    y = jnp.matmul(x, w)
    y = shard(y, *([None] * y.ndim))  # replicated feature dim (post-psum)
    if b is not None:
        y = y + b
    return y


def sequence_shard(x, batch_axis_spec="dp"):
    """Sequence-parallel activation layout [B, T, D] with T split over the
    sequence axis — used between transformer blocks so layernorm/dropout/
    elementwise work is also divided (Megatron-SP). Attention/MLP regions
    re-gather via their own shardings."""
    return shard(x, batch_axis_spec, SEQUENCE_AXIS, None)


# ===========================================================================
# explicit tp collective ops — spliced by framework/sharding.py's
# tp_shard_pass, executed INSIDE the ParallelExecutor's full-manual
# shard_map region where the `tp` axis name is bound (the same contract as
# grad_comm's dp_grad_comm / dp_shard_* ops on the dp axis).
#
# Every op carries "count-once" differentiation semantics: the manual
# executor computes the (identical) loss on every tp shard and seeds each
# shard's backward with 1, so jax's default collective transposes (psum ->
# psum of cotangents) would multiply gradients by tp. The custom VJPs below
# implement the Megatron f/g operator pair instead:
#
#   tp_allreduce  fwd psum        bwd identity      (g: row-parallel psum)
#   tp_ident      fwd identity    bwd psum          (f: column-parallel in)
#   tp_split      fwd local slice bwd all-gather    (lm-head row entry)
#   tp_allgather  fwd all-gather  bwd local slice   (tp<->dp reshard)
#   tp_vocab_lookup  masked local lookup + psum     (vocab-sharded / EP emb)
# ===========================================================================

from ..core.enforce import InvalidArgumentError, enforce  # noqa: E402
from ..framework.registry import (register_effects, register_infer_spec,  # noqa: E402
                                  register_op, register_shard_spec)

# The executor's shard_map wrapper publishes the traced tp shard index here
# (same mechanism and rationale as grad_comm._CURRENT_DP_INDEX: a
# tp-sharded arange sliced to the local entry is the index form every
# jax/XLA version accepts inside the manual region).
_CURRENT_TP_INDEX: list = []


class tp_index_scope:
    """Context manager binding the traced tp shard index for op lowerings."""

    def __init__(self, idx):
        self.idx = idx

    def __enter__(self):
        _CURRENT_TP_INDEX.append(self.idx)

    def __exit__(self, *a):
        _CURRENT_TP_INDEX.pop()


def current_tp_index(axis_name: str):
    if _CURRENT_TP_INDEX:
        return _CURRENT_TP_INDEX[-1]
    return jax.lax.axis_index(axis_name)


def psum_once(x, axis_name: str):
    """psum whose backward is the identity: the value becomes replicated,
    and the (replicated, identical) downstream cotangent passes through
    unscaled — Megatron's g operator."""

    @jax.custom_vjp
    def f(x):
        return jax.lax.psum(x, axis_name)

    f.defvjp(lambda x: (f(x), None), lambda _, g: (g,))
    return f(x)


def ident_psum_grad(x, axis_name: str):
    """Identity whose backward psums the cotangent: wraps a replicated
    activation entering tp-sharded compute, so the partial cotangents the
    sharded branches produce are reduced — Megatron's f operator."""

    @jax.custom_vjp
    def f(x):
        return x

    f.defvjp(lambda x: (x, None),
             lambda _, g: (jax.lax.psum(g, axis_name),))
    return f(x)


def split_once(x, axis_name: str, dim: int, parts: int, idx):
    """Local slice of a replicated value along `dim`; backward all-gathers
    the per-shard cotangent slices back into the full cotangent (each
    shard's slice is the exact gradient of its chunk — disjoint, so gather
    reassembles without a sum)."""
    dim = dim if dim >= 0 else dim + x.ndim
    chunk = x.shape[dim] // parts

    @jax.custom_vjp
    def f(x):
        return jax.lax.dynamic_slice_in_dim(x, idx * chunk, chunk,
                                            axis=dim)

    f.defvjp(lambda x: (f(x), None),
             lambda _, g: (jax.lax.all_gather(g, axis_name, axis=dim,
                                              tiled=True),))
    return f(x)


def gather_once(x, axis_name: str, dim: int, idx):
    """All-gather a sharded value back to replicated; backward slices the
    (replicated) cotangent back to the local chunk."""
    dim = dim if dim >= 0 else dim + x.ndim
    chunk = x.shape[dim]

    @jax.custom_vjp
    def f(x):
        return jax.lax.all_gather(x, axis_name, axis=dim, tiled=True)

    f.defvjp(lambda x: (f(x), None),
             lambda _, g: (jax.lax.dynamic_slice_in_dim(
                 g, idx * chunk, chunk, axis=dim),))
    return f(x)


@register_op("tp_allreduce")
def _tp_allreduce(ctx, ins, attrs):
    return {"Out": [psum_once(ins["X"][0], attrs["axis"])]}


@register_op("tp_ident")
def _tp_ident(ctx, ins, attrs):
    return {"Out": [ident_psum_grad(ins["X"][0], attrs["axis"])]}


@register_op("tp_split")
def _tp_split(ctx, ins, attrs):
    axis = attrs["axis"]
    return {"Out": [split_once(ins["X"][0], axis, int(attrs["dim"]),
                               int(attrs["parts"]),
                               current_tp_index(axis))]}


@register_op("tp_allgather")
def _tp_allgather(ctx, ins, attrs):
    axis = attrs["axis"]
    return {"Out": [gather_once(ins["X"][0], axis, int(attrs["dim"]),
                                current_tp_index(axis))]}


@register_op("tp_vocab_lookup")
def _tp_vocab_lookup(ctx, ins, attrs):
    """Embedding lookup over a vocab-row-sharded table (the distributed
    lookup table / EP analogue, reference distribute_transpiler.py:212):
    ids are global, each shard holds rows [i*V/p, (i+1)*V/p); out-of-range
    rows contribute zero and the psum assembles the full lookup. The table
    gradient stays local (scatter-add into the shard's rows only)."""
    w = ins["W"][0]
    ids = ins["Ids"][0]
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = jnp.squeeze(ids, axis=-1)
    axis = attrs["axis"]
    idx = current_tp_index(axis)
    vshard = w.shape[0]
    local = ids - (idx * vshard).astype(ids.dtype)
    ok = (local >= 0) & (local < vshard)
    padding_idx = attrs.get("padding_idx", None)
    if padding_idx is not None:
        pad = padding_idx if padding_idx >= 0 \
            else padding_idx + int(attrs["vocab"])
        ok = ok & (ids != pad)
    out = jnp.take(w, jnp.clip(local, 0, vshard - 1), axis=0)
    out = out * ok[..., None].astype(out.dtype)
    return {"Out": [psum_once(out, axis)]}


# -- static-analysis infer specs + sharding rules (registered alongside,
# the framework/analysis.py + framework/sharding.py contract: these ops run
# collectives over the tp axis, so the analyzer cannot abstract-evaluate
# them standalone) ----------------------------------------------------------


@register_infer_spec("tp_allreduce")
def _infer_tp_allreduce(ictx, in_shapes, in_dtypes, attrs):
    return {"Out": [(in_shapes["X"][0], in_dtypes["X"][0])]}


@register_infer_spec("tp_ident")
def _infer_tp_ident(ictx, in_shapes, in_dtypes, attrs):
    return {"Out": [(in_shapes["X"][0], in_dtypes["X"][0])]}


@register_infer_spec("tp_split")
def _infer_tp_split(ictx, in_shapes, in_dtypes, attrs):
    shape = list(in_shapes["X"][0])
    dim = int(attrs["dim"])
    parts = int(attrs["parts"])
    enforce(shape[dim] % parts == 0,
            f"tp_split dim {dim} of size {shape[dim]} not divisible by "
            f"parts={parts}", exc=InvalidArgumentError)
    shape[dim] //= parts
    return {"Out": [(tuple(shape), in_dtypes["X"][0])]}


@register_infer_spec("tp_allgather")
def _infer_tp_allgather(ictx, in_shapes, in_dtypes, attrs):
    shape = list(in_shapes["X"][0])
    shape[int(attrs["dim"])] *= int(attrs["parts"])
    return {"Out": [(tuple(shape), in_dtypes["X"][0])]}


@register_infer_spec("tp_vocab_lookup")
def _infer_tp_vocab_lookup(ictx, in_shapes, in_dtypes, attrs):
    ids = list(in_shapes["Ids"][0])
    if len(ids) >= 2 and ids[-1] == 1:
        ids = ids[:-1]
    w = in_shapes["W"][0]
    return {"Out": [(tuple(ids) + tuple(w[1:]), in_dtypes["W"][0])]}


@register_shard_spec("tp_allreduce")
def _shardrule_tp_allreduce(sctx, in_specs, attrs):
    xs = in_specs["X"][0]
    return {"Out": [None if xs is None else (None,) * len(xs)]}


@register_shard_spec("tp_ident")
def _shardrule_tp_ident(sctx, in_specs, attrs):
    return {"Out": [in_specs["X"][0]]}


@register_shard_spec("tp_split")
def _shardrule_tp_split(sctx, in_specs, attrs):
    xs = in_specs["X"][0]
    if xs is None:
        return {}
    out = list(xs)
    out[int(attrs["dim"])] = sctx.axis
    return {"Out": [tuple(out)]}


@register_shard_spec("tp_allgather")
def _shardrule_tp_allgather(sctx, in_specs, attrs):
    xs = in_specs["X"][0]
    if xs is None:
        return {}
    out = list(xs)
    out[int(attrs["dim"])] = None
    return {"Out": [tuple(out)]}


@register_shard_spec("tp_vocab_lookup")
def _shardrule_tp_vocab_lookup(sctx, in_specs, attrs):
    ids_shape = sctx.in_shape("Ids")
    rank = len(ids_shape) if ids_shape else 2
    if ids_shape and len(ids_shape) >= 2 and ids_shape[-1] == 1:
        rank -= 1
    ws = in_specs["W"][0]
    return {"Out": [(None,) * (rank + (len(ws) - 1 if ws else 1))]}


# -- dataflow effect sets (framework/dataflow.py): which mesh axis each op
# communicates over and what its output's consistency over that axis is.
# The backward halves count too — tp_ident/tp_split's collectives live in
# their custom VJPs, but a shard that skips the op skips those psums/
# gathers just the same, so deadlock analysis treats them as collectives.


@register_effects("tp_allreduce")
def _eff_tp_allreduce(op):
    a = op.attrs.get("axis")
    # fwd psum: every shard's partial goes in, the identical sum comes out
    return {"collective_axes": (a,), "resolves_axes": (a,)}


@register_effects("tp_ident")
def _eff_tp_ident(op):
    # fwd identity (taints ride through); bwd psums the cotangent
    return {"collective_axes": (op.attrs.get("axis"),)}


@register_effects("tp_split")
def _eff_tp_split(op):
    a = op.attrs.get("axis")
    # fwd local slice: the output deliberately VARIES per shard
    return {"collective_axes": (a,), "shards_axes": (a,)}


@register_effects("tp_allgather")
def _eff_tp_allgather(op):
    a = op.attrs.get("axis")
    return {"collective_axes": (a,), "resolves_axes": (a,)}


@register_effects("tp_vocab_lookup")
def _eff_tp_vocab_lookup(op):
    a = op.attrs.get("axis")
    # masked local lookup + psum: replicated result from a sharded table
    return {"collective_axes": (a,), "resolves_axes": (a,)}
