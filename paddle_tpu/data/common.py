"""Dataset download/cache helpers.

≙ reference python/paddle/dataset/common.py:1 (DATA_HOME, download with
md5 verification and retry, md5file). This environment usually has no
network egress, so `download` is strictly opt-in: datasets use it only
when the file is absent and a URL fetch is possible; everything else
falls back to the synthetic generators (datasets.py).
"""

from __future__ import annotations

import hashlib
import os
import shutil
from typing import Optional

from ..core.enforce import InvalidArgumentError, enforce


def data_home() -> str:
    """Current cache root (env PTPU_DATA_HOME; datasets.DATA_HOME mirrors
    it for back-compat)."""
    from . import datasets
    return datasets.DATA_HOME


def md5file(path: str) -> str:
    """≙ common.md5file — streaming md5 of a file."""
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url: str, module: str, md5sum: Optional[str] = None,
             save_name: Optional[str] = None, retries: int = 3) -> str:
    """≙ common.download: fetch `url` into <DATA_HOME>/<module>/, verify
    md5, reuse the cached copy when it already matches. Supports file://
    URLs (used by offline tests and air-gapped mirrors)."""
    directory = os.path.join(data_home(), module)
    os.makedirs(directory, exist_ok=True)
    filename = os.path.join(directory,
                            save_name or url.split("/")[-1].split("?")[0])
    if os.path.exists(filename) and (md5sum is None
                                     or md5file(filename) == md5sum):
        return filename

    import urllib.request
    last_err = None
    for _ in range(max(1, retries)):
        try:
            tmp = filename + ".part"
            with urllib.request.urlopen(url, timeout=60) as r, \
                    open(tmp, "wb") as f:
                shutil.copyfileobj(r, f)
            if md5sum is not None and md5file(tmp) != md5sum:
                os.remove(tmp)
                last_err = InvalidArgumentError(
                    f"md5 mismatch downloading {url}")
                continue
            os.replace(tmp, filename)
            return filename
        except Exception as e:  # noqa: BLE001 — retried, then re-raised
            last_err = e
    raise InvalidArgumentError(
        f"could not download {url} after {retries} attempts "
        f"(no network egress? place the file at {filename} manually): "
        f"{last_err}")


def cached_path(module: str, filename: str) -> str:
    return os.path.join(data_home(), module, filename)


def exists(module: str, filename: str) -> bool:
    return os.path.exists(cached_path(module, filename))


def tokenize(text: str):
    """≙ reference imdb.tokenize: lowercase, strip punctuation, split."""
    import re
    return re.sub(r"[^a-z0-9\s]", "", text.lower()).split()


def build_word_dict(corpus_iter, min_word_freq: int = 0,
                    unk_token: str = "<unk>"):
    """Frequency-sorted word -> id dict (≙ imdb.build_dict /
    imikolov.build_dict): most frequent word gets id 0; words under
    min_word_freq drop out; unk_token appended last."""
    enforce(min_word_freq >= 0, "min_word_freq must be >= 0",
            exc=InvalidArgumentError)
    freq: dict = {}
    for tokens in corpus_iter:
        for t in tokens:
            freq[t] = freq.get(t, 0) + 1
    items = [(w, c) for w, c in freq.items()
             if c >= min_word_freq and w != unk_token]
    items.sort(key=lambda wc: (-wc[1], wc[0]))
    word_idx = {w: i for i, (w, _) in enumerate(items)}
    word_idx[unk_token] = len(word_idx)
    return word_idx
