"""Ragged-batch packing: many variable-length sequences → few fixed rows.

The TPU-throughput translation of the reference's LoD ragged batches
(reference paddle/fluid/framework/lod_tensor.h:58, whose point is training
without padding): sequences are packed back to back into static-shape rows
and a segment-id plane keeps them from attending to / counting against each
other (flash kernel segment masking, ops/pallas_kernels.py; loss masking,
models/transformer.py packed=True).

Conventions: segment id 0 = padding; real sequences get 1..N per row.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np


def pack_sequences(seqs: Sequence[np.ndarray], max_len: int,
                   pad_value=0) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """First-fit pack 1-D sequences into [B, max_len] rows.

    Returns (tokens [B, max_len], segments [B, max_len] int32, positions
    [B, max_len] int32 — position WITHIN the owning segment, so positional
    encodings are pack-placement-invariant). Sequences longer than max_len
    are truncated. Greedy first-fit: each sequence goes into the first row
    with room, a new row opens when none fits — O(n·rows), fine for
    batch-sized inputs.
    """
    rows: List[List[np.ndarray]] = []
    room: List[int] = []
    for s in seqs:
        s = np.asarray(s)[:max_len]
        placed = False
        for i, r in enumerate(room):
            if len(s) <= r:
                rows[i].append(s)
                room[i] -= len(s)
                placed = True
                break
        if not placed:
            rows.append([s])
            room.append(max_len - len(s))
    B = len(rows)
    dtype = np.asarray(seqs[0]).dtype if len(seqs) else np.int64
    tokens = np.full((B, max_len), pad_value, dtype=dtype)
    segments = np.zeros((B, max_len), np.int32)
    positions = np.zeros((B, max_len), np.int32)
    for b, row in enumerate(rows):
        off = 0
        for j, s in enumerate(row):
            tokens[b, off:off + len(s)] = s
            segments[b, off:off + len(s)] = j + 1
            positions[b, off:off + len(s)] = np.arange(len(s))
            off += len(s)
    return tokens, segments, positions


def pack_lm_batch(seqs: Sequence[np.ndarray], max_len: int,
                  pad_id: int = 0) -> Dict[str, np.ndarray]:
    """Pack sequences for models.transformer.transformer_lm(packed=True):
    feed dict of tokens / segments / next-token targets. The model itself
    masks out padding and segment-final tokens (whose successor belongs to
    another sequence) from the loss, in-graph from `segments`."""
    tokens, segments, positions = pack_sequences(seqs, max_len,
                                                 pad_value=pad_id)
    targets = np.full_like(tokens, pad_id)
    targets[:, :-1] = tokens[:, 1:]
    return {"tokens": tokens, "segments": segments,
            "positions": positions, "targets": targets}
