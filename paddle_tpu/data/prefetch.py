"""Async host→device prefetch.

≙ reference double-buffered readers (operators/reader/buffered_reader.h:27,
create_double_buffer_reader_op.cc) and the py_reader blocking queue
(reader/lod_tensor_blocking_queue.h:31). TPU translation: a worker thread
stages upcoming batches onto the device with jax.device_put while the current
step runs, overlapping host input with device compute.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, Iterator, Optional

import jax


class DevicePrefetcher:
    """Wrap a feed-dict iterator; yields batches already resident on device.

    `stage_threads` workers stage batches CONCURRENTLY (order preserved via
    futures): on links with per-transfer latency — a remote TPU tunnel's
    ~100 ms RTT, or a busy PCIe queue — a single staging stream idles the
    link between transfers; two in flight keep it saturated."""

    _END = object()

    def __init__(self, feed_iter_fn: Callable[[], Iterator[Dict]],
                 capacity: int = 2, device=None, sharding=None,
                 staging: Optional[Dict] = None, stage_threads: int = 2):
        """staging: {var_name: (wire_dtype, device_scale)} — convert those
        entries to their byte-lean wire dtype on the worker thread before
        staging (see data.feeder.staging_specs / layers.data staging_dtype).
        Through a bandwidth-limited host->device link this is the difference
        between 1/4 and full fp32 bytes per image batch."""
        self._fn = feed_iter_fn
        self._capacity = max(capacity, stage_threads)
        self._device = device
        self._sharding = sharding
        self._staging = staging or {}
        self._stage_threads = max(1, stage_threads)

    def _put(self, batch: Dict):
        if self._staging:
            from .feeder import stage_batch
            batch = stage_batch(batch, self._staging)
        target = self._sharding or self._device
        if target is None:
            return {k: jax.device_put(v) for k, v in batch.items()}
        return {k: jax.device_put(v, target) for k, v in batch.items()}

    def __iter__(self):
        from concurrent.futures import ThreadPoolExecutor

        q: queue.Queue = queue.Queue(maxsize=self._capacity)
        err = []
        pool = ThreadPoolExecutor(max_workers=self._stage_threads)
        # set when the consumer abandons the iterator (break / exception
        # in the training loop): the producer must not stay blocked in
        # put() forever, pinning its thread, the pool, and up to
        # `capacity` staged device batches for process lifetime
        closed = threading.Event()

        def put_open(item) -> bool:
            while not closed.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for b in self._fn():
                    # bounded queue of FUTURES: up to `capacity` batches
                    # are staging/staged ahead, in iterator order
                    if not put_open(pool.submit(self._put, b)):
                        return
            except Exception as e:  # propagate to consumer
                err.append(e)
            finally:
                put_open(self._END)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is self._END:
                    if err:
                        raise err[0]
                    return
                yield item.result()
        finally:
            closed.set()
            try:  # drop queued futures so staged batches free promptly
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            pool.shutdown(wait=False)
