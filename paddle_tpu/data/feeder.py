"""DataFeeder: minibatch list-of-samples → feed dict of dense arrays.

≙ reference python/paddle/fluid/data_feeder.py (DataFeeder converting
numpy/lists to LoDTensors per feed var). Sequence (lod_level>0) slots are
padded to the batch max length and a companion `<name>@SEQLEN` int32 vector is
emitted — the static-shape translation of LoD.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from ..core.dtypes import convert_dtype
from ..framework.program import Program, Variable, default_main_program


def staging_specs(program: Program = None) -> Dict[str, tuple]:
    """Collect {var_name: (wire_dtype, device_scale)} for every data var
    declared with a staging dtype (layers.data(staging_dtype=...))."""
    program = program or default_main_program()
    out = {}
    for b in program.blocks:
        for v in b.vars.values():
            if getattr(v, "staging", None) is not None:
                out[v.name] = v.staging
    return out


def stage_array(arr: np.ndarray, spec: tuple) -> np.ndarray:
    """Convert one host array to its byte-lean wire dtype. The device side
    inverts this inside the compiled step (executor feed staging): for a
    float var staged uint8 with device scale s, host stores round(x/s)
    so device x' = uint8 * s reproduces x to 1/2-ulp-of-s accuracy."""
    wire_dtype, scale = spec
    wire_dtype = convert_dtype(wire_dtype)  # canonical np.dtype
    if np.asarray(arr).dtype == wire_dtype:
        return np.asarray(arr)  # already on the wire grid: don't re-quantize
    if scale is not None:
        info = np.iinfo(wire_dtype) if np.issubdtype(
            wire_dtype, np.integer) else None
        x = np.rint(np.asarray(arr, np.float32) / scale)
        if info is not None:
            x = np.clip(x, info.min, info.max)
        return x.astype(wire_dtype)
    return np.asarray(arr).astype(wire_dtype)


def stage_batch(feed: Dict[str, np.ndarray],
                specs: Dict[str, tuple]) -> Dict[str, np.ndarray]:
    """Apply stage_array to every feed entry with a staging spec."""
    if not specs:
        return feed
    return {k: stage_array(v, specs[k]) if k in specs else v
            for k, v in feed.items()}


class DataFeeder:
    def __init__(self, feed_list: Sequence, place=None, program: Program = None):
        program = program or default_main_program()
        self.feed_vars: List[Variable] = [
            program.global_block().var(v) if isinstance(v, str) else v
            for v in feed_list]
        self.place = place

    def feed(self, minibatch: Sequence[Sequence]) -> Dict[str, np.ndarray]:
        """minibatch: list of samples, each a tuple aligned with feed_list."""
        out: Dict[str, np.ndarray] = {}
        for i, var in enumerate(self.feed_vars):
            col = [sample[i] for sample in minibatch]
            dtype = convert_dtype(var.dtype)
            if var.lod_level > 0:
                seqs = [np.asarray(s, dtype=dtype) for s in col]
                maxlen = max(s.shape[0] for s in seqs)
                trailing = seqs[0].shape[1:]
                padded = np.zeros((len(seqs), maxlen) + trailing, dtype=dtype)
                lengths = np.zeros(len(seqs), dtype=np.int32)
                for j, s in enumerate(seqs):
                    padded[j, :s.shape[0]] = s
                    lengths[j] = s.shape[0]
                out[var.name] = padded
                out[var.name + "@SEQLEN"] = lengths
            else:
                arr = np.asarray(col, dtype=dtype)
                # match declared trailing shape, e.g. labels [N] -> [N, 1]
                want = [d for d in (var.shape or []) if d != -1]
                if want and list(arr.shape[1:]) != want and \
                        int(np.prod(arr.shape[1:])) == int(np.prod(want)):
                    arr = arr.reshape([arr.shape[0]] + want)
                out[var.name] = arr
        return out
