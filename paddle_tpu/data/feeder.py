"""DataFeeder: minibatch list-of-samples → feed dict of dense arrays.

≙ reference python/paddle/fluid/data_feeder.py (DataFeeder converting
numpy/lists to LoDTensors per feed var). Sequence (lod_level>0) slots are
padded to the batch max length and a companion `<name>@SEQLEN` int32 vector is
emitted — the static-shape translation of LoD.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from ..core.dtypes import convert_dtype
from ..framework.program import Program, Variable, default_main_program


class DataFeeder:
    def __init__(self, feed_list: Sequence, place=None, program: Program = None):
        program = program or default_main_program()
        self.feed_vars: List[Variable] = [
            program.global_block().var(v) if isinstance(v, str) else v
            for v in feed_list]
        self.place = place

    def feed(self, minibatch: Sequence[Sequence]) -> Dict[str, np.ndarray]:
        """minibatch: list of samples, each a tuple aligned with feed_list."""
        out: Dict[str, np.ndarray] = {}
        for i, var in enumerate(self.feed_vars):
            col = [sample[i] for sample in minibatch]
            dtype = convert_dtype(var.dtype)
            if var.lod_level > 0:
                seqs = [np.asarray(s, dtype=dtype) for s in col]
                maxlen = max(s.shape[0] for s in seqs)
                trailing = seqs[0].shape[1:]
                padded = np.zeros((len(seqs), maxlen) + trailing, dtype=dtype)
                lengths = np.zeros(len(seqs), dtype=np.int32)
                for j, s in enumerate(seqs):
                    padded[j, :s.shape[0]] = s
                    lengths[j] = s.shape[0]
                out[var.name] = padded
                out[var.name + "@SEQLEN"] = lengths
            else:
                arr = np.asarray(col, dtype=dtype)
                # match declared trailing shape, e.g. labels [N] -> [N, 1]
                want = [d for d in (var.shape or []) if d != -1]
                if want and list(arr.shape[1:]) != want and \
                        int(np.prod(arr.shape[1:])) == int(np.prod(want)):
                    arr = arr.reshape([arr.shape[0]] + want)
                out[var.name] = arr
        return out
