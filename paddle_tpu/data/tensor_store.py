"""ctypes binding for the native tensor container (tensor_store.cc).

≙ reference save_combine_op.cc / load_combine_op.cc + LoDTensor
SerializeToStream (framework/lod_tensor.cc): many named tensors in one
CRC-checked file, streamed through C++. io.save_vars/load_vars (and the
save/load_params/persistables wrappers) route any ``filename`` ending in
``.pts`` through this container; other filenames use the portable npz path.
"""

from __future__ import annotations

import ctypes
from typing import Dict, List

import numpy as np

try:  # registers the bfloat16/float16 numpy dtypes
    import ml_dtypes  # noqa: F401
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    pass

from .recordio import _load  # shared library loader (builds on demand)

_DTYPES = ["float32", "float64", "int32", "int64", "uint8", "bool",
           "bfloat16", "float16", "int16", "uint32", "uint64"]
_CODE = {name: i for i, name in enumerate(_DTYPES)}


def _lib():
    lib = _load()
    lib.ptpu_store_writer_open.restype = ctypes.c_void_p
    lib.ptpu_store_writer_open.argtypes = [ctypes.c_char_p]
    lib.ptpu_store_writer_add.restype = ctypes.c_int
    lib.ptpu_store_writer_add.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint8,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint8,
        ctypes.c_void_p, ctypes.c_uint64]
    lib.ptpu_store_writer_finish.restype = ctypes.c_int
    lib.ptpu_store_writer_finish.argtypes = [ctypes.c_void_p]
    lib.ptpu_store_reader_open.restype = ctypes.c_void_p
    lib.ptpu_store_reader_open.argtypes = [ctypes.c_char_p]
    lib.ptpu_store_reader_names.restype = ctypes.c_uint64
    lib.ptpu_store_reader_names.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
    lib.ptpu_store_reader_meta.restype = ctypes.c_uint64
    lib.ptpu_store_reader_meta.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint64)]
    lib.ptpu_store_reader_read.restype = ctypes.c_int
    lib.ptpu_store_reader_read.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64]
    lib.ptpu_store_reader_close.restype = None
    lib.ptpu_store_reader_close.argtypes = [ctypes.c_void_p]
    return lib


def _np_dtype_name(arr) -> str:
    name = arr.dtype.name
    if name not in _CODE:
        raise ValueError(f"tensor_store: unsupported dtype {name!r}")
    return name


def save_tensors(path: str, tensors: Dict[str, np.ndarray]):
    """Write named arrays into one native container file. Atomic: data goes
    to a temp file that replaces `path` only after a successful finalize, so
    a mid-save failure can never leave a valid-looking partial checkpoint
    over the previous good one."""
    import os
    lib = _lib()
    tmp = path + ".tmp"
    h = lib.ptpu_store_writer_open(tmp.encode())
    if not h:
        raise IOError(f"tensor_store: cannot open {tmp!r} for writing")
    try:
        for name, arr in tensors.items():
            arr = np.ascontiguousarray(arr)
            if arr.ndim > 16:
                raise ValueError(
                    f"tensor_store: {name!r} has {arr.ndim} dims; the "
                    f"container supports at most 16")
            # bfloat16 arrays pass through as raw bytes with their code
            code = _CODE[_np_dtype_name(arr)]
            dims = (ctypes.c_uint64 * max(arr.ndim, 1))(*arr.shape)
            # hand C++ the array's own buffer — no tobytes() copy; `arr`
            # stays referenced for the duration of the call
            ok = lib.ptpu_store_writer_add(
                h, name.encode(), code, dims, arr.ndim,
                ctypes.c_void_p(arr.ctypes.data), arr.nbytes)
            if not ok:
                raise IOError(f"tensor_store: write failed for {name!r}")
    except Exception:
        lib.ptpu_store_writer_finish(h)   # release the handle...
        try:
            os.unlink(tmp)                # ...and discard the partial file
        except OSError:
            pass
        raise
    if not lib.ptpu_store_writer_finish(h):
        raise IOError(f"tensor_store: finalize failed for {path!r}")
    os.replace(tmp, path)


def load_tensors(path: str, names: List[str] = None) -> Dict[str, np.ndarray]:
    """Read (a subset of) named arrays back; every payload is
    CRC-verified."""
    lib = _lib()
    h = lib.ptpu_store_reader_open(path.encode())
    if not h:
        raise IOError(_open_error(path))
    try:
        n = lib.ptpu_store_reader_names(h, None, 0)
        buf = ctypes.create_string_buffer(int(n))
        lib.ptpu_store_reader_names(h, buf, n)
        available = buf.raw[:int(n)].decode().split("\n") if n else []
        wanted = available if names is None else list(names)
        out: Dict[str, np.ndarray] = {}
        for name in wanted:
            dtype = ctypes.c_uint8()
            ndim = ctypes.c_uint8()
            dims = (ctypes.c_uint64 * 16)()
            dlen = lib.ptpu_store_reader_meta(
                h, name.encode(), ctypes.byref(dtype), ctypes.byref(ndim),
                dims)
            if dlen == ctypes.c_uint64(-1).value:
                raise KeyError(f"tensor_store: {name!r} not in {path!r}")
            raw = ctypes.create_string_buffer(int(dlen))
            if not lib.ptpu_store_reader_read(h, name.encode(), raw, dlen):
                raise IOError(
                    f"tensor_store: CRC/read failure for {name!r} "
                    f"in {path!r}")
            shape = tuple(dims[i] for i in range(ndim.value))
            arr = np.frombuffer(raw.raw[:int(dlen)],
                                dtype=_DTYPES[dtype.value]).reshape(shape)
            out[name] = arr.copy()
        return out
    finally:
        lib.ptpu_store_reader_close(h)


def _format_version() -> int:
    """The native library is the single source of truth for the format."""
    lib = _load()
    lib.ptpu_store_version.restype = ctypes.c_uint32
    return int(lib.ptpu_store_version())


def _open_error(path: str) -> str:
    """Distinguish 'wrong container version' from genuine corruption."""
    import os as _os
    import struct
    if not _os.path.exists(path):
        return f"tensor_store: {path!r} does not exist"
    try:
        with open(path, "rb") as f:
            head = f.read(8)
        magic, version = struct.unpack("<II", head)
        current = _format_version()
        if magic == 0x50545453 and version != current:
            return (f"tensor_store: {path!r} is container format "
                    f"v{version}; this build reads v{current} — "
                    f"re-save the checkpoint with the current version")
    except Exception:
        pass
    return (f"tensor_store: cannot open {path!r} "
            f"(truncated or corrupt index)")


def list_tensors(path: str) -> List[str]:
    lib = _lib()
    h = lib.ptpu_store_reader_open(path.encode())
    if not h:
        raise IOError(_open_error(path))
    try:
        n = lib.ptpu_store_reader_names(h, None, 0)
        buf = ctypes.create_string_buffer(int(n))
        lib.ptpu_store_reader_names(h, buf, n)
        return buf.raw[:int(n)].decode().split("\n") if n else []
    finally:
        lib.ptpu_store_reader_close(h)
