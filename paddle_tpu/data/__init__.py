"""Data layer: reader decorators, datasets, feeder, device prefetch.

≙ reference python/paddle/reader/ + python/paddle/dataset/ + the C++
reader-op pipeline (SURVEY §1 L10). The in-graph reader ops translate to a
host-side prefetching pipeline feeding compiled steps.
"""

from . import common  # noqa: F401
from . import datasets  # noqa: F401
from .common import download, md5file  # noqa: F401
from .decorator import (batch, buffered, chain, compose, firstn,  # noqa: F401
                        map_readers, shuffle, xmap_readers)
from .feeder import (DataFeeder, stage_array, stage_batch,  # noqa: F401
                     staging_specs)
from .packing import pack_lm_batch, pack_sequences  # noqa: F401
from .prefetch import DevicePrefetcher  # noqa: F401
from .recordio import (ParallelRecordLoader, RecordIOScanner,  # noqa: F401
                       RecordIOWriter, read_numpy_records,
                       write_numpy_records)
