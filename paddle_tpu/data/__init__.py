"""Data layer: reader decorators, datasets, feeder, device prefetch.

≙ reference python/paddle/reader/ + python/paddle/dataset/ + the C++
reader-op pipeline (SURVEY §1 L10). The in-graph reader ops translate to a
host-side prefetching pipeline feeding compiled steps.
"""

from . import datasets  # noqa: F401
from .decorator import (batch, buffered, chain, compose, firstn,  # noqa: F401
                        map_readers, shuffle, xmap_readers)
from .feeder import DataFeeder  # noqa: F401
from .prefetch import DevicePrefetcher  # noqa: F401
from .recordio import (ParallelRecordLoader, RecordIOScanner,  # noqa: F401
                       RecordIOWriter, read_numpy_records,
                       write_numpy_records)
