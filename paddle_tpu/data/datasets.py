"""Builtin datasets.

≙ reference python/paddle/dataset/ (mnist, cifar, imdb, uci_housing,
imikolov, ...). This environment has no network egress, so each dataset is
backed by a deterministic synthetic generator with the same sample shapes and
reader contract; if the real files exist under PTPU_DATA_HOME they are used
instead. The reader API (train()/test() -> reader) matches the reference.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Callable

import numpy as np

DATA_HOME = os.environ.get("PTPU_DATA_HOME",
                           os.path.expanduser("~/.cache/paddle_tpu/dataset"))


def _synthetic_images(n, shape, classes, seed):
    rng = np.random.RandomState(seed)
    w = rng.randn(int(np.prod(shape)), classes).astype(np.float32)

    def reader():
        r = np.random.RandomState(seed + 1)
        for _ in range(n):
            x = r.rand(*shape).astype(np.float32)
            y = int(np.argmax(x.reshape(-1) @ w))
            yield x, y

    return reader


# ------------------------------------------------------------------ mnist
def _mnist_files_exist():
    d = os.path.join(DATA_HOME, "mnist")
    return all(os.path.exists(os.path.join(d, f)) for f in
               ["train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz"])


def _read_mnist(img_path, lbl_path):
    with gzip.open(lbl_path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), dtype=np.uint8)
    with gzip.open(img_path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows * cols)
    images = images.astype(np.float32) / 127.5 - 1.0

    def reader():
        for i in range(n):
            yield images[i], int(labels[i])

    return reader


class mnist:
    """≙ paddle.dataset.mnist — 784-dim float images in [-1,1], int label."""

    @staticmethod
    def train() -> Callable:
        if _mnist_files_exist():
            d = os.path.join(DATA_HOME, "mnist")
            return _read_mnist(os.path.join(d, "train-images-idx3-ubyte.gz"),
                               os.path.join(d, "train-labels-idx1-ubyte.gz"))
        return _synthetic_images(8192, (784,), 10, seed=7)

    @staticmethod
    def test() -> Callable:
        if _mnist_files_exist():
            d = os.path.join(DATA_HOME, "mnist")
            return _read_mnist(os.path.join(d, "t10k-images-idx3-ubyte.gz"),
                               os.path.join(d, "t10k-labels-idx1-ubyte.gz"))
        return _synthetic_images(1024, (784,), 10, seed=8)


def _read_cifar_tar(tar_path, member_substr, label_key=b"labels"):
    """Parse the REAL CIFAR python pickle format: a tar.gz whose members
    hold pickled dicts {b'data': [N, 3072] uint8, b'labels'/b'fine_labels':
    [N]} (≙ reference dataset/cifar.py reader_creator). Images normalize
    to float32 / 255."""
    import pickle
    import tarfile

    def reader():
        with tarfile.open(tar_path, "r:*") as tf:
            for m in sorted(tf.getnames()):
                if member_substr not in os.path.basename(m):
                    continue
                f = tf.extractfile(m)
                if f is None:
                    continue
                batch = pickle.loads(f.read(), encoding="bytes")
                data = np.asarray(batch[b"data"], np.uint8)
                labels = batch.get(label_key, batch.get(b"labels"))
                for x, y in zip(data, labels):
                    yield x.astype(np.float32) / 255.0, int(y)

    return reader


def _cifar_tar(name):
    p = os.path.join(DATA_HOME, "cifar", name)
    return p if os.path.exists(p) else None


class cifar:
    """≙ paddle.dataset.cifar — 3x32x32 images. Real CIFAR-10/100 python
    pickle tars are parsed when present under <DATA_HOME>/cifar/ (or
    fetched via data.common.download where network exists); synthetic
    stand-ins otherwise."""

    TAR10 = "cifar-10-python.tar.gz"
    TAR100 = "cifar-100-python.tar.gz"

    @staticmethod
    def train10():
        tar = _cifar_tar(cifar.TAR10)
        if tar:
            return _read_cifar_tar(tar, "data_batch")
        return _synthetic_images(8192, (3 * 32 * 32,), 10, seed=17)

    @staticmethod
    def test10():
        tar = _cifar_tar(cifar.TAR10)
        if tar:
            return _read_cifar_tar(tar, "test_batch")
        return _synthetic_images(1024, (3 * 32 * 32,), 10, seed=18)

    @staticmethod
    def train100():
        tar = _cifar_tar(cifar.TAR100)
        if tar:
            return _read_cifar_tar(tar, "train", label_key=b"fine_labels")
        return _synthetic_images(8192, (3 * 32 * 32,), 100, seed=19)


class uci_housing:
    """≙ paddle.dataset.uci_housing — 13 features, scalar target."""

    @staticmethod
    def train():
        rng = np.random.RandomState(3)
        w = rng.randn(13).astype(np.float32)

        def reader():
            r = np.random.RandomState(4)
            for _ in range(404):
                x = r.rand(13).astype(np.float32)
                y = float(x @ w + 0.05 * r.randn())
                yield x, np.array([y], dtype=np.float32)

        return reader

    @staticmethod
    def test():
        rng = np.random.RandomState(3)
        w = rng.randn(13).astype(np.float32)

        def reader():
            r = np.random.RandomState(5)
            for _ in range(102):
                x = r.rand(13).astype(np.float32)
                yield x, np.array([float(x @ w)], dtype=np.float32)

        return reader


def _imdb_tar():
    p = os.path.join(DATA_HOME, "imdb", "aclImdb_v1.tar.gz")
    return p if os.path.exists(p) else None


def _read_imdb_tar(tar_path, pattern, word_dict):
    """Parse the REAL aclImdb layout: tar.gz of <split>/<pos|neg>/<id>.txt
    review files (≙ reference dataset/imdb.py reader_creator). pos -> 0,
    neg -> 1, as in the reference."""
    import re
    import tarfile

    from .common import tokenize
    unk = word_dict.get("<unk>", len(word_dict) - 1)
    rx = re.compile(pattern)

    def reader():
        with tarfile.open(tar_path, "r:*") as tf:
            for m in sorted(tf.getnames()):
                if not rx.search(m):
                    continue
                f = tf.extractfile(m)
                if f is None:
                    continue
                toks = tokenize(f.read().decode("utf-8", "replace"))
                ids = np.asarray([word_dict.get(t, unk) for t in toks],
                                 np.int64)
                if ids.size == 0:
                    continue
                yield ids, (0 if "/pos/" in m else 1)

    return reader


def _imdb_build_dict(tar_path, min_word_freq=5):
    import re
    import tarfile

    from .common import build_word_dict, tokenize

    def corpus():
        rx = re.compile(r"train/(pos|neg)/.*\.txt$")
        with tarfile.open(tar_path, "r:*") as tf:
            for m in tf.getnames():
                if rx.search(m):
                    f = tf.extractfile(m)
                    if f is not None:
                        yield tokenize(f.read().decode("utf-8", "replace"))

    return build_word_dict(corpus(), min_word_freq=min_word_freq)


class imdb:
    """≙ paddle.dataset.imdb — variable-length word-id sequences, binary
    label. The real aclImdb tar is parsed when present under
    <DATA_HOME>/imdb/ (word dict built from the train split, frequency
    sorted, ≙ reference imdb.build_dict); synthetic class-dependent
    unigram distributions otherwise."""

    word_dict_size = 5148

    @staticmethod
    def word_dict(min_word_freq=5):
        tar = _imdb_tar()
        if tar:
            return _imdb_build_dict(tar, min_word_freq)
        return {i: i for i in range(imdb.word_dict_size)}

    @staticmethod
    def _make(seed, n):
        def reader():
            r = np.random.RandomState(seed)
            v = imdb.word_dict_size
            for _ in range(n):
                label = int(r.rand() > 0.5)
                length = int(r.randint(20, 200))
                center = v // 4 if label == 0 else 3 * v // 4
                ids = np.clip(r.normal(center, v // 8, length), 0, v - 1) \
                    .astype(np.int64)
                yield ids, label

        return reader

    @staticmethod
    def train(word_dict=None):
        tar = _imdb_tar()
        if tar:
            wd = word_dict if word_dict is not None else imdb.word_dict()
            return _read_imdb_tar(tar, r"train/(pos|neg)/.*\.txt$", wd)
        return imdb._make(11, 2048)

    @staticmethod
    def test(word_dict=None):
        tar = _imdb_tar()
        if tar:
            wd = word_dict if word_dict is not None else imdb.word_dict()
            return _read_imdb_tar(tar, r"test/(pos|neg)/.*\.txt$", wd)
        return imdb._make(12, 512)


def _imikolov_file(split):
    p = os.path.join(DATA_HOME, "imikolov", f"ptb.{split}.txt")
    return p if os.path.exists(p) else None


def _read_imikolov_text(path, word_dict, n):
    """Parse the REAL PTB text format: one sentence per line, wrapped in
    <s>/<e> markers, emitted as sliding n-grams of word ids (≙ reference
    dataset/imikolov.py reader_creator with DataType.NGRAM)."""
    unk = word_dict.get("<unk>", len(word_dict) - 1)

    def reader():
        with open(path, encoding="utf-8") as f:
            for line in f:
                words = ["<s>"] + line.split() + ["<e>"]
                ids = [word_dict.get(w, unk) for w in words]
                for i in range(n, len(ids) + 1):
                    yield tuple(ids[i - n:i])

    return reader


class imikolov:
    """≙ paddle.dataset.imikolov — PTB-style n-gram language model data.
    Real ptb.<split>.txt files are parsed when present under
    <DATA_HOME>/imikolov/; synthetic markov-ish n-grams otherwise."""

    vocab_size = 2074

    @staticmethod
    def build_dict(min_word_freq=50):
        path = _imikolov_file("train")
        if path:
            from .common import build_word_dict

            def corpus():
                with open(path, encoding="utf-8") as f:
                    for line in f:
                        yield ["<s>"] + line.split() + ["<e>"]

            return build_word_dict(corpus(), min_word_freq=min_word_freq)
        return {i: i for i in range(imikolov.vocab_size)}

    @staticmethod
    def _make(seed, n, ngram):
        def reader():
            r = np.random.RandomState(seed)
            v = imikolov.vocab_size
            # markov-ish: next word correlated with sum of context
            for _ in range(n):
                ctx = r.randint(0, v, size=ngram - 1)
                nxt = int((ctx.sum() * 31 + r.randint(0, 7)) % v)
                yield tuple(int(c) for c in ctx) + (nxt,)

        return reader

    @staticmethod
    def train(word_dict=None, n=5):
        path = _imikolov_file("train")
        if path:
            wd = word_dict if word_dict is not None \
                else imikolov.build_dict()
            return _read_imikolov_text(path, wd, n)
        return imikolov._make(21, 4096, n)

    @staticmethod
    def test(word_dict=None, n=5):
        path = _imikolov_file("valid")
        if path:
            wd = word_dict if word_dict is not None \
                else imikolov.build_dict()
            return _read_imikolov_text(path, wd, n)
        return imikolov._make(22, 512, n)


class ptb:
    """PTB-style token stream for the stacked-LSTM LM benchmark."""

    vocab_size = 10000

    @staticmethod
    def train(seq_len=20, n=2048):
        def reader():
            r = np.random.RandomState(31)
            for _ in range(n):
                seq = r.randint(0, ptb.vocab_size, size=seq_len + 1)
                yield seq[:-1].astype(np.int64), seq[1:].astype(np.int64)

        return reader


class wmt_synthetic:
    """Synthetic parallel corpus for the Transformer NMT benchmark
    (≙ paddle.dataset.wmt14/wmt16 shapes)."""

    src_vocab = 10000
    trg_vocab = 10000
    bos, eos = 0, 1

    @staticmethod
    def train(n=2048, max_len=30, seed=41):
        def reader():
            r = np.random.RandomState(seed)
            for _ in range(n):
                slen = int(r.randint(5, max_len))
                src = r.randint(2, wmt_synthetic.src_vocab, size=slen)
                trg = (src[:max(1, slen - 1)] + 7) % wmt_synthetic.trg_vocab
                trg = np.clip(trg, 2, None)
                yield (src.astype(np.int64),
                       np.concatenate([[wmt_synthetic.bos], trg]).astype(np.int64),
                       np.concatenate([trg, [wmt_synthetic.eos]]).astype(np.int64))

        return reader


class ctr_synthetic:
    """Synthetic CTR data (sparse id features + dense) for DeepFM/Wide&Deep
    (≙ the distributed-lookup-table workload, SURVEY §2.3)."""

    @staticmethod
    def train(n=4096, num_fields=26, vocab_per_field=1000, dense_dim=13):
        def reader():
            r = np.random.RandomState(51)
            w_sparse = np.random.RandomState(52).randn(num_fields)
            w_dense = np.random.RandomState(53).randn(dense_dim)
            for _ in range(n):
                sparse = r.randint(0, vocab_per_field, size=num_fields)
                dense = r.rand(dense_dim).astype(np.float32)
                logit = (sparse / vocab_per_field - 0.5) @ w_sparse + \
                    dense @ w_dense
                label = int(logit + 0.3 * r.randn() > 0)
                yield sparse.astype(np.int64), dense, label

        return reader


# ------------------------------------------------------------- flowers
class flowers:
    """≙ reference dataset/flowers.py (102-category Oxford flowers):
    224x224x3 images + label."""

    NUM_CLASSES = 102

    @staticmethod
    def train(n=512):
        return _synthetic_images(n, (3, 224, 224), flowers.NUM_CLASSES, 101)

    @staticmethod
    def test(n=128):
        return _synthetic_images(n, (3, 224, 224), flowers.NUM_CLASSES, 102)

    valid = test


# ----------------------------------------------------------- movielens
class movielens:
    """≙ reference dataset/movielens.py: (user_id, gender, age, job,
    movie_id, category vec, title vec) -> rating."""

    MAX_USER = 6040
    MAX_MOVIE = 3952
    NUM_JOBS = 21
    NUM_AGES = 7
    NUM_CATEGORIES = 18
    TITLE_LEN = 10
    TITLE_VOCAB = 5000

    @staticmethod
    def _reader(n, seed):
        def reader():
            r = np.random.RandomState(seed)
            for _ in range(n):
                user = r.randint(1, movielens.MAX_USER + 1)
                gender = r.randint(0, 2)
                age = r.randint(0, movielens.NUM_AGES)
                job = r.randint(0, movielens.NUM_JOBS)
                movie = r.randint(1, movielens.MAX_MOVIE + 1)
                cats = r.randint(0, movielens.NUM_CATEGORIES,
                                 (r.randint(1, 4),))
                title = r.randint(0, movielens.TITLE_VOCAB,
                                  (movielens.TITLE_LEN,))
                # learnable structure: rating depends on ids
                rating = float((user * 7 + movie * 3) % 5 + 1)
                yield (user, gender, age, job, movie, cats, title, rating)
        return reader

    @staticmethod
    def train(n=2048):
        return movielens._reader(n, 201)

    @staticmethod
    def test(n=512):
        return movielens._reader(n, 202)

    @staticmethod
    def max_user_id():
        return movielens.MAX_USER

    @staticmethod
    def max_movie_id():
        return movielens.MAX_MOVIE

    @staticmethod
    def max_job_id():
        return movielens.NUM_JOBS - 1

    @staticmethod
    def age_table():
        return [1, 18, 25, 35, 45, 50, 56]


# -------------------------------------------------------------- conll05
class conll05:
    """≙ reference dataset/conll05.py (semantic role labeling). Yields the
    reference's 9 slots: (word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2,
    predicate, mark, label) where ctx_* are the +-2 context windows around
    the predicate position broadcast over the sequence."""

    WORD_VOCAB = 4000
    LABEL_DICT_LEN = 59   # reference label dict size
    PRED_VOCAB = 3000

    @staticmethod
    def get_dict():
        word_dict = {f"w{i}": i for i in range(conll05.WORD_VOCAB)}
        verb_dict = {f"v{i}": i for i in range(conll05.PRED_VOCAB)}
        label_dict = {f"l{i}": i for i in range(conll05.LABEL_DICT_LEN)}
        return word_dict, verb_dict, label_dict

    @staticmethod
    def _reader(n, seed, max_len=30):
        def reader():
            r = np.random.RandomState(seed)
            for _ in range(n):
                t = int(r.randint(5, max_len + 1))
                words = r.randint(0, conll05.WORD_VOCAB, (t,))
                pred_pos = int(r.randint(0, t))
                pred = r.randint(0, conll05.PRED_VOCAB)
                # +-2 context window around the predicate, broadcast over
                # the sequence (the reference's ctx_n2..ctx_p2 slots)
                def ctx(offset):
                    j = min(max(pred_pos + offset, 0), t - 1)
                    return np.full((t,), words[j], dtype=np.int64)
                mark = np.zeros((t,), dtype=np.int64)
                mark[pred_pos] = 1
                labels = (words * 31 + pred) % conll05.LABEL_DICT_LEN
                yield (words, ctx(-2), ctx(-1), ctx(0), ctx(1), ctx(2),
                       pred, mark, labels)
        return reader

    @staticmethod
    def train(n=1024):
        return conll05._reader(n, 301)

    @staticmethod
    def test(n=256):
        return conll05._reader(n, 302)


# ------------------------------------------------------------ sentiment
class sentiment:
    """≙ reference dataset/sentiment.py (NLTK movie reviews): token id
    sequence -> 0/1 polarity."""

    VOCAB = 5000

    @staticmethod
    def get_word_dict():
        return {f"tok{i}": i for i in range(sentiment.VOCAB)}

    @staticmethod
    def _reader(n, seed):
        def reader():
            r = np.random.RandomState(seed)
            pos = r.permutation(sentiment.VOCAB)[:sentiment.VOCAB // 2]
            pos_set = set(int(x) for x in pos)
            for _ in range(n):
                t = r.randint(8, 60)
                toks = r.randint(0, sentiment.VOCAB, (t,))
                score = sum(1 if int(x) in pos_set else -1 for x in toks)
                yield toks, int(score > 0)
        return reader

    @staticmethod
    def train(n=1024):
        return sentiment._reader(n, 401)

    @staticmethod
    def test(n=256):
        return sentiment._reader(n, 402)


# -------------------------------------------------------------- voc2012
class voc2012:
    """≙ reference dataset/voc2012.py (segmentation): image [3,H,W] +
    dense label map [H,W] with 21 classes."""

    NUM_CLASSES = 21

    @staticmethod
    def _reader(n, seed, size=128):
        def reader():
            r = np.random.RandomState(seed)
            for _ in range(n):
                img = r.rand(3, size, size).astype(np.float32)
                # blocky label map correlated with intensity (learnable)
                lbl = (img.mean(0) * voc2012.NUM_CLASSES).astype(np.int64)
                lbl = np.clip(lbl, 0, voc2012.NUM_CLASSES - 1)
                yield img, lbl
        return reader

    @staticmethod
    def train(n=256):
        return voc2012._reader(n, 501)

    @staticmethod
    def test(n=64):
        return voc2012._reader(n, 502)

    val = test


# ------------------------------------------------------------ wmt14/16
class wmt14:
    """≙ reference dataset/wmt14.py: (src ids, tgt ids, tgt_next ids)."""

    DICT_SIZE = 30000

    @staticmethod
    def train(dict_size=DICT_SIZE, n=2048, max_len=30):
        return wmt_synthetic.train(n=n, max_len=max_len)

    @staticmethod
    def test(dict_size=DICT_SIZE, n=512, max_len=30):
        # distinct stream from train (seed 42 vs 41): evaluating on
        # training samples would silently inflate metrics
        return wmt_synthetic.train(n=n, max_len=max_len, seed=42)


class wmt16(wmt14):
    """≙ reference dataset/wmt16.py — same reader contract."""


# --------------------------------------------------------------- mq2007
class mq2007:
    """≙ reference dataset/mq2007.py (LETOR learning-to-rank): per query a
    list of (feature[46], relevance) pairs; pairwise/listwise modes."""

    FEATURE_DIM = 46

    @staticmethod
    def _reader(n_queries, seed, format="pairwise"):
        def reader():
            r = np.random.RandomState(seed)
            w = r.randn(mq2007.FEATURE_DIM).astype(np.float32)
            for _ in range(n_queries):
                docs = r.randint(5, 20)
                feats = r.rand(docs, mq2007.FEATURE_DIM).astype(np.float32)
                rel = ((feats @ w) > 0).astype(np.int64) + \
                    ((feats @ w) > 1).astype(np.int64)
                if format == "listwise":
                    yield feats, rel
                else:  # pairwise: yield (query-level) doc pairs d1 > d2
                    for i in range(docs):
                        for j in range(docs):
                            if rel[i] > rel[j]:
                                yield rel[i] - rel[j], feats[i], feats[j]
        return reader

    @staticmethod
    def train(format="pairwise", n_queries=128):
        return mq2007._reader(n_queries, 601, format)

    @staticmethod
    def test(format="pairwise", n_queries=32):
        return mq2007._reader(n_queries, 602, format)
