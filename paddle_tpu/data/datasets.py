"""Builtin datasets.

≙ reference python/paddle/dataset/ (mnist, cifar, imdb, uci_housing,
imikolov, ...). This environment has no network egress, so each dataset is
backed by a deterministic synthetic generator with the same sample shapes and
reader contract; if the real files exist under PTPU_DATA_HOME they are used
instead. The reader API (train()/test() -> reader) matches the reference.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Callable

import numpy as np

DATA_HOME = os.environ.get("PTPU_DATA_HOME",
                           os.path.expanduser("~/.cache/paddle_tpu/dataset"))


def _synthetic_images(n, shape, classes, seed):
    rng = np.random.RandomState(seed)
    w = rng.randn(int(np.prod(shape)), classes).astype(np.float32)

    def reader():
        r = np.random.RandomState(seed + 1)
        for _ in range(n):
            x = r.rand(*shape).astype(np.float32)
            y = int(np.argmax(x.reshape(-1) @ w))
            yield x, y

    return reader


# ------------------------------------------------------------------ mnist
def _mnist_files_exist():
    d = os.path.join(DATA_HOME, "mnist")
    return all(os.path.exists(os.path.join(d, f)) for f in
               ["train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz"])


def _read_mnist(img_path, lbl_path):
    with gzip.open(lbl_path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), dtype=np.uint8)
    with gzip.open(img_path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows * cols)
    images = images.astype(np.float32) / 127.5 - 1.0

    def reader():
        for i in range(n):
            yield images[i], int(labels[i])

    return reader


class mnist:
    """≙ paddle.dataset.mnist — 784-dim float images in [-1,1], int label."""

    @staticmethod
    def train() -> Callable:
        if _mnist_files_exist():
            d = os.path.join(DATA_HOME, "mnist")
            return _read_mnist(os.path.join(d, "train-images-idx3-ubyte.gz"),
                               os.path.join(d, "train-labels-idx1-ubyte.gz"))
        return _synthetic_images(8192, (784,), 10, seed=7)

    @staticmethod
    def test() -> Callable:
        if _mnist_files_exist():
            d = os.path.join(DATA_HOME, "mnist")
            return _read_mnist(os.path.join(d, "t10k-images-idx3-ubyte.gz"),
                               os.path.join(d, "t10k-labels-idx1-ubyte.gz"))
        return _synthetic_images(1024, (784,), 10, seed=8)


class cifar:
    """≙ paddle.dataset.cifar — 3x32x32 images."""

    @staticmethod
    def train10():
        return _synthetic_images(8192, (3 * 32 * 32,), 10, seed=17)

    @staticmethod
    def test10():
        return _synthetic_images(1024, (3 * 32 * 32,), 10, seed=18)

    @staticmethod
    def train100():
        return _synthetic_images(8192, (3 * 32 * 32,), 100, seed=19)


class uci_housing:
    """≙ paddle.dataset.uci_housing — 13 features, scalar target."""

    @staticmethod
    def train():
        rng = np.random.RandomState(3)
        w = rng.randn(13).astype(np.float32)

        def reader():
            r = np.random.RandomState(4)
            for _ in range(404):
                x = r.rand(13).astype(np.float32)
                y = float(x @ w + 0.05 * r.randn())
                yield x, np.array([y], dtype=np.float32)

        return reader

    @staticmethod
    def test():
        rng = np.random.RandomState(3)
        w = rng.randn(13).astype(np.float32)

        def reader():
            r = np.random.RandomState(5)
            for _ in range(102):
                x = r.rand(13).astype(np.float32)
                yield x, np.array([float(x @ w)], dtype=np.float32)

        return reader


class imdb:
    """≙ paddle.dataset.imdb — variable-length word-id sequences, binary
    label. Synthetic: class-dependent unigram distributions."""

    word_dict_size = 5148

    @staticmethod
    def word_dict():
        return {i: i for i in range(imdb.word_dict_size)}

    @staticmethod
    def _make(seed, n):
        def reader():
            r = np.random.RandomState(seed)
            v = imdb.word_dict_size
            for _ in range(n):
                label = int(r.rand() > 0.5)
                length = int(r.randint(20, 200))
                center = v // 4 if label == 0 else 3 * v // 4
                ids = np.clip(r.normal(center, v // 8, length), 0, v - 1) \
                    .astype(np.int64)
                yield ids, label

        return reader

    @staticmethod
    def train(word_dict=None):
        return imdb._make(11, 2048)

    @staticmethod
    def test(word_dict=None):
        return imdb._make(12, 512)


class imikolov:
    """≙ paddle.dataset.imikolov — PTB-style n-gram language model data."""

    vocab_size = 2074

    @staticmethod
    def build_dict(min_word_freq=50):
        return {i: i for i in range(imikolov.vocab_size)}

    @staticmethod
    def _make(seed, n, ngram):
        def reader():
            r = np.random.RandomState(seed)
            v = imikolov.vocab_size
            # markov-ish: next word correlated with sum of context
            for _ in range(n):
                ctx = r.randint(0, v, size=ngram - 1)
                nxt = int((ctx.sum() * 31 + r.randint(0, 7)) % v)
                yield tuple(int(c) for c in ctx) + (nxt,)

        return reader

    @staticmethod
    def train(word_dict=None, n=5):
        return imikolov._make(21, 4096, n)

    @staticmethod
    def test(word_dict=None, n=5):
        return imikolov._make(22, 512, n)


class ptb:
    """PTB-style token stream for the stacked-LSTM LM benchmark."""

    vocab_size = 10000

    @staticmethod
    def train(seq_len=20, n=2048):
        def reader():
            r = np.random.RandomState(31)
            for _ in range(n):
                seq = r.randint(0, ptb.vocab_size, size=seq_len + 1)
                yield seq[:-1].astype(np.int64), seq[1:].astype(np.int64)

        return reader


class wmt_synthetic:
    """Synthetic parallel corpus for the Transformer NMT benchmark
    (≙ paddle.dataset.wmt14/wmt16 shapes)."""

    src_vocab = 10000
    trg_vocab = 10000
    bos, eos = 0, 1

    @staticmethod
    def train(n=2048, max_len=30):
        def reader():
            r = np.random.RandomState(41)
            for _ in range(n):
                slen = int(r.randint(5, max_len))
                src = r.randint(2, wmt_synthetic.src_vocab, size=slen)
                trg = (src[:max(1, slen - 1)] + 7) % wmt_synthetic.trg_vocab
                trg = np.clip(trg, 2, None)
                yield (src.astype(np.int64),
                       np.concatenate([[wmt_synthetic.bos], trg]).astype(np.int64),
                       np.concatenate([trg, [wmt_synthetic.eos]]).astype(np.int64))

        return reader


class ctr_synthetic:
    """Synthetic CTR data (sparse id features + dense) for DeepFM/Wide&Deep
    (≙ the distributed-lookup-table workload, SURVEY §2.3)."""

    @staticmethod
    def train(n=4096, num_fields=26, vocab_per_field=1000, dense_dim=13):
        def reader():
            r = np.random.RandomState(51)
            w_sparse = np.random.RandomState(52).randn(num_fields)
            w_dense = np.random.RandomState(53).randn(dense_dim)
            for _ in range(n):
                sparse = r.randint(0, vocab_per_field, size=num_fields)
                dense = r.rand(dense_dim).astype(np.float32)
                logit = (sparse / vocab_per_field - 0.5) @ w_sparse + \
                    dense @ w_dense
                label = int(logit + 0.3 * r.randn() > 0)
                yield sparse.astype(np.int64), dense, label

        return reader
