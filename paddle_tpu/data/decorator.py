"""Functional reader decorators.

≙ reference python/paddle/reader/decorator.py:33-240 (map_readers, shuffle,
chain, compose, buffered, firstn, xmap_readers). A reader is a zero-arg
callable returning an iterable over samples — identical contract to the
reference so user pipelines port unchanged.
"""

from __future__ import annotations

import itertools
import queue
import random
import threading
from typing import Callable, Iterable, List

from ..core.enforce import InvalidArgumentError, enforce


def map_readers(func, *readers):
    """Apply func elementwise over parallel readers (≙ decorator.py:33)."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    """Shuffle within a sliding buffer (≙ decorator.py shuffle)."""

    def shuffled():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            random.shuffle(buf)
            yield from buf

    return shuffled


def chain(*readers):
    """Concatenate readers (≙ decorator.py chain)."""

    def chained():
        for r in readers:
            yield from r()

    return chained


def compose(*readers, check_alignment=True):
    """Zip readers into tuple samples (≙ decorator.py compose)."""

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def composed():
        rs = [r() for r in readers]
        if check_alignment:
            for items in itertools.zip_longest(*rs):
                enforce(all(i is not None for i in items),
                        "readers have different lengths",
                        exc=InvalidArgumentError)
                yield sum((make_tuple(i) for i in items), ())
        else:
            for items in zip(*rs):
                yield sum((make_tuple(i) for i in items), ())

    return composed


def buffered(reader, size):
    """Prefetch into a bounded queue on a worker thread (≙ decorator.py
    buffered) — hides host-side read latency from the training loop."""

    end = object()

    def buffered_reader():
        q: queue.Queue = queue.Queue(maxsize=size)

        def fill():
            try:
                for d in reader():
                    q.put(d)
            finally:
                q.put(end)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is end:
                break
            yield e

    return buffered_reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i >= n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map with worker threads (≙ decorator.py xmap_readers)."""

    end = object()

    def xreader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)

        def read_worker():
            for i, sample in enumerate(reader()):
                in_q.put((i, sample))
            for _ in range(process_num):
                in_q.put(end)

        def map_worker():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, sample = item
                out_q.put((i, mapper(sample)))

        threading.Thread(target=read_worker, daemon=True).start()
        workers = [threading.Thread(target=map_worker, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()
        finished = 0
        pending = {}
        next_idx = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            if order:
                i, mapped = item
                pending[i] = mapped
                while next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
            else:
                yield item[1]
        if order:
            for i in sorted(pending):
                yield pending[i]

    return xreader


def batch(reader, batch_size, drop_last=True):
    """Group samples into lists (≙ python/paddle/v2-era batch.py /
    paddle.batch)."""

    def batch_reader():
        b = []
        for item in reader():
            b.append(item)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader
