"""RecordIO: chunked CRC-checked record files + native threaded loader.

Python surface over the C++ runtime (paddle_tpu/native/recordio.cc), the
capability equivalent of the reference's RecordIO container
(reference: paddle/fluid/recordio/{writer,scanner,chunk}.h) and the C++
reader pipeline (reference: operators/reader/buffered_reader.h:27,
lod_tensor_blocking_queue.h:31, open_files_op.cc). Bindings are ctypes —
this toolchain has no pybind11; the .so is built on demand with g++.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Iterator, List, Optional, Sequence

from ..core.enforce import InvalidArgumentError, NotFoundError, enforce

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libptpu_native.so")

_lib = None


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    # stale if older than ANY native source (the .so bundles every .cc)
    srcs = [os.path.join(_NATIVE_DIR, f) for f in os.listdir(_NATIVE_DIR)
            if f.endswith(".cc") or f == "build.sh"]
    if (not os.path.exists(_SO_PATH) or
            os.path.getmtime(_SO_PATH) < max(os.path.getmtime(s)
                                             for s in srcs)):
        subprocess.run(["sh", os.path.join(_NATIVE_DIR, "build.sh")],
                       check=True, capture_output=True)
    lib = ctypes.CDLL(_SO_PATH)
    lib.rio_writer_open.restype = ctypes.c_void_p
    lib.rio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                    ctypes.c_int]
    lib.rio_writer_write.restype = ctypes.c_int
    lib.rio_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_uint32]
    lib.rio_writer_flush.restype = ctypes.c_int
    lib.rio_writer_flush.argtypes = [ctypes.c_void_p]
    lib.rio_writer_close.restype = ctypes.c_int
    lib.rio_writer_close.argtypes = [ctypes.c_void_p]
    lib.rio_scanner_open.restype = ctypes.c_void_p
    lib.rio_scanner_open.argtypes = [ctypes.c_char_p]
    lib.rio_scanner_next.restype = ctypes.POINTER(ctypes.c_char)
    lib.rio_scanner_next.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_uint32)]
    lib.rio_scanner_skipped.restype = ctypes.c_uint32
    lib.rio_scanner_skipped.argtypes = [ctypes.c_void_p]
    lib.rio_scanner_close.argtypes = [ctypes.c_void_p]
    lib.rio_loader_open.restype = ctypes.c_void_p
    lib.rio_loader_open.argtypes = [ctypes.POINTER(ctypes.c_char_p),
                                    ctypes.c_int, ctypes.c_int,
                                    ctypes.c_uint32]
    lib.rio_loader_next.restype = ctypes.POINTER(ctypes.c_char)
    lib.rio_loader_next.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_uint32)]
    lib.rio_loader_failed_files.restype = ctypes.c_uint32
    lib.rio_loader_failed_files.argtypes = [ctypes.c_void_p]
    lib.rio_loader_skipped.restype = ctypes.c_uint32
    lib.rio_loader_skipped.argtypes = [ctypes.c_void_p]
    lib.rio_loader_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class RecordIOWriter:
    """Append records (bytes) to a chunked file; context manager closes.

    ≙ recordio::Writer (reference recordio/writer.h)."""

    def __init__(self, path: str, max_chunk_bytes: int = 1 << 20,
                 compress: bool = False):
        lib = _load()
        self._lib = lib
        self._h = lib.rio_writer_open(path.encode(), max_chunk_bytes,
                                      1 if compress else 0)
        enforce(self._h, f"cannot open {path!r} for writing",
                exc=NotFoundError)

    def _handle(self):
        enforce(self._h, "writer is closed", exc=InvalidArgumentError)
        return self._h

    def write(self, record: bytes):
        enforce(isinstance(record, (bytes, bytearray)),
                "record must be bytes", exc=InvalidArgumentError)
        rc = self._lib.rio_writer_write(self._handle(), bytes(record),
                                        len(record))
        enforce(rc == 0, "recordio write failed")

    def flush(self):
        enforce(self._lib.rio_writer_flush(self._handle()) == 0,
                "flush failed")

    def close(self):
        if self._h:
            self._lib.rio_writer_close(self._h)
            self._h = None

    def __del__(self):  # flush the buffered tail chunk if never closed
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RecordIOScanner:
    """Iterate records of one file; corrupt chunks are skipped (resync on
    the chunk magic) and counted in .skipped_chunks.

    ≙ recordio::Scanner (reference recordio/scanner.h)."""

    def __init__(self, path: str):
        lib = _load()
        self._lib = lib
        self._h = lib.rio_scanner_open(path.encode())
        enforce(self._h, f"cannot open {path!r}", exc=NotFoundError)

    def _handle(self):
        enforce(self._h, "scanner is closed", exc=InvalidArgumentError)
        return self._h

    def __iter__(self) -> Iterator[bytes]:
        n = ctypes.c_uint32()
        while True:
            p = self._lib.rio_scanner_next(self._handle(), ctypes.byref(n))
            if not p:
                return
            yield ctypes.string_at(p, n.value)

    @property
    def skipped_chunks(self) -> int:
        return self._lib.rio_scanner_skipped(self._handle())

    def close(self):
        if self._h:
            self._lib.rio_scanner_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ParallelRecordLoader:
    """N native threads scan a file list into a bounded in-memory queue;
    iterate to consume. The C++ analogue of the reference's
    open_files + double_buffer reader stack."""

    def __init__(self, paths: Sequence[str], num_threads: int = 4,
                 queue_capacity: int = 256):
        enforce(len(paths) > 0, "need at least one file",
                exc=InvalidArgumentError)
        missing = [p for p in paths if not os.path.exists(p)]
        enforce(not missing, f"recordio files not found: {missing}",
                exc=NotFoundError)
        lib = _load()
        self._lib = lib
        arr = (ctypes.c_char_p * len(paths))(*[p.encode() for p in paths])
        self._h = lib.rio_loader_open(arr, len(paths), num_threads,
                                      queue_capacity)
        enforce(self._h, "loader open failed")

    def __iter__(self) -> Iterator[bytes]:
        n = ctypes.c_uint32()
        while True:
            enforce(self._h, "loader is closed", exc=InvalidArgumentError)
            p = self._lib.rio_loader_next(self._h, ctypes.byref(n))
            if not p:
                # workers are done; a file that raced past the ctor
                # existence check (deleted/unreadable) must not pass as
                # silent data loss
                failed = self._lib.rio_loader_failed_files(self._h)
                if failed:
                    raise IOError(f"{failed} recordio file(s) could not "
                                  f"be opened by the loader")
                return
            yield ctypes.string_at(p, n.value)

    @property
    def skipped_chunks(self) -> int:
        """Corrupt chunks skipped (summed over finished files)."""
        enforce(self._h, "loader is closed", exc=InvalidArgumentError)
        return self._lib.rio_loader_skipped(self._h)

    def close(self):
        if self._h:
            self._lib.rio_loader_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_numpy_records(path: str, arrays_iter, compress: bool = False):
    """Serialize an iterable of numpy-array tuples as records (npz-free
    compact framing: npy bytes per field)."""
    import io as _io

    import numpy as np
    with RecordIOWriter(path, compress=compress) as w:
        count = 0
        for tup in arrays_iter:
            if not isinstance(tup, (list, tuple)):
                tup = (tup,)
            buf = _io.BytesIO()
            buf.write(np.array(len(tup), dtype="<u4").tobytes())
            for a in tup:
                f = _io.BytesIO()
                np.save(f, np.asarray(a), allow_pickle=False)
                b = f.getvalue()
                buf.write(np.array(len(b), dtype="<u4").tobytes())
                buf.write(b)
            w.write(buf.getvalue())
            count += 1
    return count


def read_numpy_records(source) -> Iterator[tuple]:
    """Inverse of write_numpy_records; `source` is a Scanner/Loader or an
    iterable of raw record bytes."""
    import io as _io

    import numpy as np
    for rec in source:
        off = 0
        nf = int(np.frombuffer(rec, "<u4", 1, off)[0])
        off += 4
        out = []
        for _ in range(nf):
            ln = int(np.frombuffer(rec, "<u4", 1, off)[0])
            off += 4
            out.append(np.load(_io.BytesIO(rec[off:off + ln]),
                               allow_pickle=False))
            off += ln
        yield tuple(out)
