"""DeepFM / Wide&Deep CTR models (driver config #5 — the capability slot of
the reference's sparse/pserver path: distributed lookup table +
SelectedRows-style sparse embedding, reference lookup_table_op.cc:21 with
is_sparse/is_distributed and transpiler distributed_lookup_table).

TPU-first: the embedding table is a dense shardable array; at scale it is
sharded over the mesh via paddle_tpu.parallel.sharded_embedding (all-to-all
gather — the EP analogue)."""

from __future__ import annotations

from .. import layers


def deepfm(feat_ids=None, feat_vals=None, label=None, num_fields=39,
           vocab_size=100000, embed_dim=16, fc_sizes=(400, 400, 400),
           is_sparse=False, fuse_first_order=True, row_pad=None):
    """DeepFM: linear term + FM second-order term + DNN over concatenated
    field embeddings.

    feat_ids: [B, num_fields] int64; feat_vals: [B, num_fields] float32;
    label: [B, 1] float32 in {0, 1}.

    fuse_first_order (TPU optimization, on by default): the first-order
    scalar weights live as column 0 of ONE [vocab, 1 + embed_dim] table
    instead of a separate [vocab, 1] table. Identical model capacity, but
    half the table lookups/scatter-updates per step — on TPU those
    small-row gathers/scatters are tile-granularity-bound and dominate
    sparse-CTR step time (round-3 profiling: ~5-10 ms device time each).

    row_pad (TPU optimization, opt-in): physically pad the fused table's
    row to this width (a 128-lane tile multiple, e.g. 128) and slice the
    logical columns after lookup. A [vocab, 17] table gets a vocab-MINOR
    layout whose scatter/gather rows straddle ~17 separate (8,128) tiles;
    at 128-wide rows every gathered/scattered row is one tile line. Model
    capacity is unchanged: the pad columns carry zero gradient, and lazy
    (sparse) Adam leaves their moments at exactly 0. Round-4 profiling:
    the sparse step is scatter-bound (84 ms of which ~60 ms is the three
    row-scatters); row_pad=128 cut it to 35 ms. Default None keeps the
    logical table shape so checkpoints saved before round 4 still load.
    """
    if feat_ids is None:
        feat_ids = layers.data(name="feat_ids", shape=[num_fields],
                               dtype="int64")
    if feat_vals is None:
        feat_vals = layers.data(name="feat_vals", shape=[num_fields])
    if label is None:
        label = layers.data(name="label", shape=[1])

    vals3 = layers.unsqueeze(feat_vals, axes=[2])                     # [B,F,1]
    if fuse_first_order:
        # one table, one lookup: [:, :, 0:1] is the linear weight, the
        # rest is the FM/DNN embedding
        width = 1 + embed_dim
        if row_pad:
            width = -(-width // row_pad) * row_pad
        fused = layers.embedding(input=feat_ids,
                                 size=[vocab_size, width],
                                 is_sparse=is_sparse)                 # [B,F,W]
        w1 = layers.slice(fused, axes=[2], starts=[0], ends=[1])
        emb = layers.slice(fused, axes=[2], starts=[1],
                           ends=[1 + embed_dim])
    else:
        if row_pad:
            raise NotImplementedError(
                "row_pad tile-aligns the FUSED table; with "
                "fuse_first_order=False pass row_pad=None (the unfused "
                "[vocab,1]/[vocab,E] tables keep their logical widths)")
        # first-order: per-feature scalar weight
        w1 = layers.embedding(input=feat_ids, size=[vocab_size, 1],
                              is_sparse=is_sparse)                    # [B,F,1]
        emb = layers.embedding(input=feat_ids,
                               size=[vocab_size, embed_dim],
                               is_sparse=is_sparse)
    first = layers.reduce_sum(layers.elementwise_mul(w1, vals3), dim=[1])

    # second-order FM: 0.5 * ((sum v)^2 - sum v^2)
    emb = layers.elementwise_mul(emb, vals3)                          # [B,F,E]
    sum_v = layers.reduce_sum(emb, dim=[1])                           # [B,E]
    sum_sq = layers.elementwise_mul(sum_v, sum_v)
    sq_sum = layers.reduce_sum(layers.elementwise_mul(emb, emb), dim=[1])
    fm = layers.scale(layers.reduce_sum(
        layers.elementwise_sub(sum_sq, sq_sum), dim=[1], keep_dim=True),
        scale=0.5)

    # deep part
    b, f = feat_ids.shape[0], num_fields
    deep = layers.reshape(emb, shape=[b, f * embed_dim])
    for size in fc_sizes:
        deep = layers.fc(deep, size=size, act="relu")
    deep_out = layers.fc(deep, size=1)

    logit = layers.elementwise_add(layers.elementwise_add(first, fm),
                                   deep_out)
    loss_vec = layers.sigmoid_cross_entropy_with_logits(logit, label)
    loss = layers.mean(loss_vec)
    pred = layers.sigmoid(logit)
    return loss, pred


def wide_and_deep(wide_ids=None, deep_ids=None, label=None, wide_fields=10,
                  deep_fields=26, wide_vocab=100000, deep_vocab=100000,
                  embed_dim=8, fc_sizes=(256, 128)):
    """Wide&Deep: linear wide part over sparse ids + DNN over embeddings."""
    if wide_ids is None:
        wide_ids = layers.data(name="wide_ids", shape=[wide_fields],
                               dtype="int64")
    if deep_ids is None:
        deep_ids = layers.data(name="deep_ids", shape=[deep_fields],
                               dtype="int64")
    if label is None:
        label = layers.data(name="label", shape=[1])
    wide_w = layers.embedding(input=wide_ids, size=[wide_vocab, 1])
    wide_out = layers.reduce_sum(wide_w, dim=[1])
    emb = layers.embedding(input=deep_ids, size=[deep_vocab, embed_dim])
    b = deep_ids.shape[0]
    deep = layers.reshape(emb, shape=[b, deep_fields * embed_dim])
    for size in fc_sizes:
        deep = layers.fc(deep, size=size, act="relu")
    deep_out = layers.fc(deep, size=1)
    logit = layers.elementwise_add(wide_out, deep_out)
    loss = layers.mean(
        layers.sigmoid_cross_entropy_with_logits(logit, label))
    pred = layers.sigmoid(logit)
    return loss, pred
