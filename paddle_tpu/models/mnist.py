"""MNIST models (≙ reference benchmark/fluid/models/mnist.py +
tests/book/test_recognize_digits.py)."""

from __future__ import annotations

from .. import layers, nets


def mlp(img=None, label=None, hidden_sizes=(128, 64), class_num=10):
    """Plain MLP (driver config #1)."""
    if img is None:
        img = layers.data(name="img", shape=[784])
    if label is None:
        label = layers.data(name="label", shape=[1], dtype="int64")
    h = img
    for size in hidden_sizes:
        h = layers.fc(h, size=size, act="relu")
    logits = layers.fc(h, size=class_num)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(logits, label)
    return loss, acc, logits


def conv_net(img=None, label=None, class_num=10):
    """LeNet-style conv net (≙ reference benchmark/fluid/models/mnist.py
    cnn_model)."""
    if img is None:
        img = layers.data(name="img", shape=[1, 28, 28])
    if label is None:
        label = layers.data(name="label", shape=[1], dtype="int64")
    conv1 = nets.simple_img_conv_pool(input=img, filter_size=5,
                                      num_filters=20, pool_size=2,
                                      pool_stride=2, act="relu")
    conv2 = nets.simple_img_conv_pool(input=conv1, filter_size=5,
                                      num_filters=50, pool_size=2,
                                      pool_stride=2, act="relu")
    logits = layers.fc(conv2, size=class_num)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(logits, label)
    return loss, acc, logits
