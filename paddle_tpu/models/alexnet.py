"""AlexNet (capability ≙ reference benchmark/paddle/image/alexnet.py — the
classic 5-conv + 3-fc ImageNet net the reference benchmarks in
benchmark/IntelOptimizedPaddle.md:59-65 train / :101-107 infer).

TPU-first construction: NHWC layout, optional bf16 activations, local
response norm omitted (LRN is a memory-bound, MXU-hostile op that modern
practice dropped; the conv/fc structure — the part the benchmark
measures — is the classic 5-conv + 3-fc net)."""

from __future__ import annotations

from .. import layers


def alexnet_imagenet(img=None, label=None, class_num=1000, is_test=False,
                     data_format="NHWC", use_bf16=False):
    if img is None:
        shape = [224, 224, 3] if data_format == "NHWC" else [3, 224, 224]
        img = layers.data(name="img", shape=shape)
    if label is None:
        label = layers.data(name="label", shape=[1], dtype="int64")

    def conv(x, ch, k, stride=1, pad=0):
        return layers.conv2d(x, num_filters=ch, filter_size=k, stride=stride,
                             padding=pad, act="relu",
                             data_format=data_format, use_bf16=use_bf16)

    def pool(x):
        return layers.pool2d(x, pool_size=3, pool_type="max", pool_stride=2,
                             data_format=data_format)

    t = pool(conv(img, 64, 11, stride=4, pad=2))
    t = pool(conv(t, 192, 5, pad=2))
    t = conv(t, 384, 3, pad=1)
    t = conv(t, 256, 3, pad=1)
    t = pool(conv(t, 256, 3, pad=1))

    t = layers.dropout(t, dropout_prob=0.5, is_test=is_test)
    t = layers.fc(t, size=4096, act="relu", use_bf16=use_bf16)
    t = layers.dropout(t, dropout_prob=0.5, is_test=is_test)
    t = layers.fc(t, size=4096, act="relu", use_bf16=use_bf16)
    logits = layers.fc(t, size=class_num)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(logits, label)
    return loss, acc, logits
